"""Path-pattern sharding rules.

The reference configures parallelism per-engine: FSDP auto-wrap policies
(ref utils/dataclasses.py:1007-1236), DeepSpeed ZeRO JSON
(ref accelerator.py:1563-1786), Megatron's hardcoded layer splits
(ref utils/megatron_lm.py). Here one concept covers all of them: an ordered
list of `(path_regex, spec_template)` rules mapping parameter *paths* to
`PartitionSpec` templates over named mesh axes. Axes absent from the actual
mesh (or not dividing the dimension) are dropped at plan time, so a single
rule set serves every mesh shape from 1 chip to a multi-slice pod.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Sequence

from ..utils.constants import AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL

# A spec template is a tuple over dims; each entry is None, an axis name, or a
# tuple of axis names (sharded over several axes).
SpecTemplate = tuple


@dataclass
class ShardingRule:
    pattern: str
    spec: SpecTemplate

    def __post_init__(self) -> None:
        self._compiled = re.compile(self.pattern)

    def matches(self, path: str) -> bool:
        return self._compiled.search(path) is not None


@dataclass
class ShardingRules:
    """Ordered rule list; first match wins. `default_fsdp` enables the
    auto-rule: shard the largest divisible dim on the fsdp axis (ZeRO-3
    semantics without any per-model annotation)."""

    rules: Sequence[ShardingRule] = field(default_factory=tuple)
    default_fsdp: bool = True
    min_weight_size: int = 2**12  # below this, replicate (ref FSDP min_num_params)

    def find(self, path: str) -> SpecTemplate | None:
        for rule in self.rules:
            if rule.matches(path):
                return rule.spec
        return None

    @classmethod
    def from_pairs(cls, pairs: Sequence[tuple[str, SpecTemplate]], **kwargs) -> "ShardingRules":
        return cls(rules=tuple(ShardingRule(p, s) for p, s in pairs), **kwargs)


# ---------------------------------------------------------------------------
# canonical transformer rule set (Megatron TP layout re-expressed as specs;
# replaces utils/megatron_lm.py's hand-split Linear layers)
# ---------------------------------------------------------------------------

# Conventions covered: our models/ naming, flax linen defaults ('kernel',
# 'embedding'), and HF-style ('weight').
TRANSFORMER_RULES: tuple[tuple[str, SpecTemplate], ...] = (
    # token embedding: (vocab, hidden) — vocab on model axis (Megatron
    # VocabParallelEmbedding), hidden on fsdp
    (r"(embed_tokens|wte|embedding|tok_embeddings).*(embedding|weight)$", (AXIS_MODEL, AXIS_FSDP)),
    # MoE experts first (more specific than the generic projections below):
    # leading expert dim on expert axis, then column/row layout
    (r"experts.*(gate_proj|up_proj|w1|w3)[/.](kernel|weight)$",
     (AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
    (r"experts.*(down_proj|w2)[/.](kernel|weight)$",
     (AXIS_EXPERT, AXIS_MODEL, AXIS_FSDP)),
    (r"router[/.](kernel|weight)$", (None, None)),
    # column-parallel (output dim sharded): q/k/v incl. fused qkv (one
    # [in, 3h] kernel whose out dim slices to per-device head groups) —
    # gpt2's `c_attn` matched NO alternative and silently replicated the
    # biggest attention matmul under tensor parallelism; neox's
    # `query_key_value` only matched through the `value` substring (the
    # rules are unanchored re.search), which is an accident, not a
    # contract — both are now named explicitly — and MLP up/gate. (in, out)
    (r"(q_proj|k_proj|v_proj|query|key|value|c_attn|query_key_value"
     r"|gate_proj|up_proj|wi|w1|w3|fc1|c_fc)[/.](kernel|weight)$",
     (AXIS_FSDP, AXIS_MODEL)),
    # row-parallel (input dim sharded): attention out, MLP down — (in, out)
    (r"(o_proj|out_proj|dense|down_proj|wo|w2|fc2|c_proj)[/.](kernel|weight)$",
     (AXIS_MODEL, AXIS_FSDP)),
    # LM head: (hidden, vocab)
    (r"(lm_head|output)[/.](kernel|weight)$", (AXIS_FSDP, AXIS_MODEL)),
    # norms / biases / scalars: replicated
    (r"(norm|ln_f|layernorm|layer_norm|rmsnorm).*", ()),
    (r"[/.](bias|scale)$", ()),
)


def transformer_rules(**kwargs) -> ShardingRules:
    return ShardingRules.from_pairs(TRANSFORMER_RULES, **kwargs)
