"""The HTTP/1.1 front door: routing, SSE streaming, overload, shutdown.

stdlib asyncio streams only — the repo's no-new-dependencies rule covers
the server too, and an inference front door needs exactly these routes:

    POST /v1/completions         OpenAI completions (+ SSE streaming)
    POST /v1/chat/completions    OpenAI chat (+ SSE streaming)
    GET  /v1/models              the one served model
    GET  /healthz                readiness (503 on drain / fired watchdog)
    GET  /metrics                Prometheus text from the engine registry
                                 (OpenMetrics + trace-id exemplars when
                                 the scraper Accepts it)
    GET  /debug/{requests,slots,pages,scheduler}
                                 read-only live introspection, gated by
                                 ServerConfig(debug_endpoints=True)
    GET  /debug/pod              role/router state when the engine is a
                                 serving.pod.PodEngine (404 on a single
                                 engine, and — like every /debug route —
                                 for every method when the gate is off)
    GET  /debug/profile?duration_s=N[&logdir=D]
                                 on-demand jax.profiler capture: records
                                 an XLA/XProf trace of the live engine
                                 for N seconds (engine keeps serving —
                                 the drive loop shares the event loop)
                                 and answers with the logdir; one
                                 capture at a time (409 while busy).
                                 Gated with the other /debug routes.

Request tracing: every generate request gets a trace id — minted fresh,
or joined from a valid inbound W3C `traceparent` header — returned as
`x-request-id` on EVERY response to that request (200, 4xx, 429, SSE
head), so a client report always names the exact trace to pull. Whether
spans record is the engine's per-tenant head-sampling decision; the id
exists regardless.

Contracts the tests pin:

- malformed JSON and oversized bodies/prompts return structured 4xx
  (OpenAI error envelope) without the scheduler ever seeing them;
- a scheduler shed/reject surfaces as 429 with a Retry-After header (the
  scheduler's own drain estimate) and a machine-readable
  `error.shed_reason` — overload is an answer, not a hang;
- a malformed `traceparent` is ignored (fresh id minted), never an error;
- a client disconnect mid-SSE-stream cancels the engine request at the
  next flush, freeing its slot and pages for the requests still paying;
- `stop()` is a graceful drain: the listener closes first, in-flight
  requests get `drain_timeout_s` to finish, stragglers are cancelled.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time
from typing import Awaitable, Callable

from ..telemetry.export import negotiate_exposition
from ..telemetry.trace import new_trace_id, parse_traceparent
from .config import ServerConfig
from .protocol import (
    SSE_DONE,
    ProtocolError,
    chat_chunk,
    chat_response,
    completion_chunk,
    completion_response,
    error_body,
    logprobs_block,
    parse_chat_request,
    parse_completion_request,
    sse_event,
    usage_block,
)
from .service import InferenceService, OverloadedError

__all__ = ["HttpFrontDoor"]

_REASONS = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
            404: "Not Found", 405: "Method Not Allowed",
            408: "Request Timeout", 409: "Conflict",
            413: "Payload Too Large",
            429: "Too Many Requests", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout"}

_MAX_HEADER_BYTES = 32 * 1024


class _BadRequest(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class _Choice:
    """Per-candidate assembly: incremental detokenization plus stop-
    sequence holdback (the last `max_stop-1` chars stay buffered until
    the choice finishes, so a stop string split across two decode steps
    still stops — and is never half-emitted)."""

    def __init__(self, tokenizer, stops: list[str]):
        self.detok = tokenizer.incremental()
        self.stops = stops
        self.holdback = max((len(s) for s in stops), default=1) - 1
        self.text = ""          # full decoded text (pre-truncation)
        self.emitted = 0        # chars already sent to the client
        self.token_ids: list[int] = []
        self.stopped = False

    def push(self, ids: list[int]) -> str:
        """Fold new token ids in; returns the text delta now safe to
        emit ("" while held back)."""
        self.token_ids.extend(ids)
        if self.stopped:
            return ""
        self.text += self.detok.push(ids)
        for s in self.stops:
            at = self.text.find(s)
            if at != -1:
                self.text = self.text[:at]
                self.stopped = True
                break
        limit = len(self.text) if self.stopped \
            else max(self.emitted, len(self.text) - self.holdback)
        delta = self.text[self.emitted:limit]
        self.emitted = limit
        return delta

    def finish(self) -> str:
        """Flush the detokenizer tail + any held-back text."""
        if not self.stopped:
            self.text += self.detok.flush()
        delta = self.text[self.emitted:]
        self.emitted = len(self.text)
        return delta


class HttpFrontDoor:
    """The server object: `await start()`, serve, `await stop()`."""

    def __init__(self, service: InferenceService,
                 config: ServerConfig | None = None):
        self.service = service
        self.config = config or service.config
        self._server: asyncio.base_events.Server | None = None
        self._inflight: set[asyncio.Task] = set()
        self._req_ids = itertools.count(1)
        self._profiling = False  # one /debug/profile capture at a time

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int | None:
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "HttpFrontDoor":
        await self.service.start()
        self._server = await asyncio.start_server(
            self._on_connection, self.config.host, self.config.port)
        return self

    async def stop(self) -> None:
        """Graceful drain: close the listener, give in-flight requests
        the drain budget, cancel the rest, then stop the engine."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.service.draining = True
        deadline = time.monotonic() + self.config.drain_timeout_s
        while self._inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        for task in list(self._inflight):
            task.cancel()
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        await self.service.stop()

    async def serve_forever(self) -> None:
        async with self._server:
            await self._server.serve_forever()

    # -- connection handling -------------------------------------------------

    def _on_connection(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.get_running_loop().create_task(
            self._handle(reader, writer))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _read_request(self, reader) -> tuple[str, str, dict, bytes]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.LimitOverrunError:
            # the StreamReader buffer limit tripped before our own header
            # cap could: still a structured 413, not a silent close
            raise _BadRequest(413, "headers too large")
        if len(head) > _MAX_HEADER_BYTES:
            raise _BadRequest(413, "headers too large")
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, _version = lines[0].split(" ", 2)
        except ValueError:
            raise _BadRequest(400, f"malformed request line {lines[0]!r}")
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep:
                raise _BadRequest(400, f"malformed header {line!r}")
            headers[name.strip().lower()] = value.strip()
        length_raw = headers.get("content-length", "0")
        try:
            length = int(length_raw)
        except ValueError:
            raise _BadRequest(400, f"bad Content-Length {length_raw!r}")
        if length < 0:
            raise _BadRequest(400, "negative Content-Length")
        if length > self.config.max_body_bytes:
            # refuse WITHOUT buffering: the body is read in chunks and
            # dropped (never held in memory) so the 413 is delivered
            # cleanly — closing with the body unread would RST the
            # connection before the client sees the error envelope
            left = length
            while left > 0:
                chunk = await reader.read(min(left, 1 << 16))
                if not chunk:
                    break
                left -= len(chunk)
            raise _BadRequest(413, f"body exceeds {self.config.max_body_bytes}"
                              " bytes")
        body = await reader.readexactly(length) if length else b""
        return method, target, headers, body

    async def _handle(self, reader, writer) -> None:
        try:
            try:
                method, target, headers, body = await asyncio.wait_for(
                    self._read_request(reader), timeout=30.0)
            except _BadRequest as e:
                await self._send_json(writer, e.status,
                                      error_body(str(e)))
                return
            except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                    ConnectionError):
                return  # the client never finished a request
            path, _, query = target.partition("?")
            await self._route(writer, method, path, query, headers, body)
        except ConnectionError:
            pass  # disconnects are handled at the streaming sites
        except Exception as e:  # a handler bug must answer 500, not hang
            try:
                await self._send_json(
                    writer, 500,
                    error_body(f"{type(e).__name__}: {e}", "server_error"))
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _route(self, writer, method: str, path: str, query: str,
                     headers: dict, body: bytes) -> None:
        handler: Callable[..., Awaitable] | None = None
        if path == "/healthz":
            handler = self._handle_health
        elif path == "/metrics":
            handler = self._handle_metrics
        elif path == "/v1/models":
            handler = self._handle_models
        elif path.startswith("/debug/") and self.config.debug_endpoints:
            # gating happens HERE, before method dispatch: disabled debug
            # routes must be indistinguishable from unknown paths (a 405
            # on POST /debug/... would fingerprint the namespace)
            handler = self._handle_debug
        elif path in ("/v1/completions", "/v1/chat/completions"):
            if method != "POST":
                await self._send_json(writer, 405, error_body(
                    f"{method} not allowed; use POST"))
                return
            await self._handle_generate(writer, path, headers, body)
            return
        if handler is None:
            await self._send_json(writer, 404,
                                  error_body(f"unknown route {path!r}"))
            return
        if method not in ("GET", "HEAD"):
            await self._send_json(writer, 405,
                                  error_body(f"{method} not allowed"))
            return
        # HEAD mirrors GET minus the body (same status/headers/length):
        # health probes HEAD /metrics and /healthz before trusting them,
        # and this route must behave like the standalone exporter's
        await handler(writer, path, query, headers, method == "HEAD")

    # -- response writing ----------------------------------------------------

    async def _send_head(self, writer, status: int, content_type: str,
                         extra: dict | None = None,
                         length: int | None = None) -> None:
        reason = _REASONS.get(status, "Unknown")
        lines = [f"HTTP/1.1 {status} {reason}",
                 f"Content-Type: {content_type}",
                 "Connection: close"]
        if length is not None:
            lines.append(f"Content-Length: {length}")
        for k, v in (extra or {}).items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        await writer.drain()

    async def _send_raw(self, writer, status: int, body: bytes,
                        content_type: str,
                        extra: dict | None = None,
                        head_only: bool = False) -> None:
        await self._send_head(writer, status, content_type, extra,
                              length=len(body))
        if not head_only:
            writer.write(body)
            await writer.drain()

    async def _send_json(self, writer, status: int, payload: dict,
                         extra: dict | None = None,
                         head_only: bool = False) -> None:
        await self._send_raw(writer, status,
                             json.dumps(payload).encode(),
                             "application/json", extra,
                             head_only=head_only)

    # -- plumbing routes -----------------------------------------------------

    async def _handle_health(self, writer, path, query, headers,
                             head_only=False) -> None:
        ok, reason = self.service.health()
        await self._send_json(writer, 200 if ok else 503,
                              {"status": "ok" if ok else "unavailable",
                               "reason": reason}, head_only=head_only)

    async def _handle_metrics(self, writer, path, query, headers,
                              head_only=False) -> None:
        # the SAME negotiation as the standalone exporter: an OpenMetrics
        # Accept gets bucket histograms with trace-id exemplars on the
        # latency series, everyone else format 0.0.4. A distributed pod
        # front merges every worker's heartbeat-shipped snapshot into the
        # exposition (telemetry/aggregate.merged_registry) — ask for that
        # richer registry when the engine offers one.
        build = getattr(self.service.engine, "exposition_registry", None)
        registry = build() if build is not None \
            else self.service.engine.registry
        text, ctype = negotiate_exposition(headers.get("accept"), registry)
        await self._send_raw(writer, 200, text.encode(), ctype,
                             head_only=head_only)

    async def _handle_models(self, writer, path, query, headers,
                             head_only=False) -> None:
        await self._send_json(writer, 200, {
            "object": "list",
            "data": [{"id": self.config.model_id, "object": "model",
                      "created": 0, "owned_by": "accelerate-tpu"}],
        }, head_only=head_only)

    async def _handle_debug(self, writer, path, query, headers,
                            head_only=False) -> None:
        """Read-only introspection. Gated OFF by default in `_route`
        (when disabled, /debug/* — any method — 404s exactly like
        unknown paths: the namespace's existence is not advertised to
        an unauthorized prober)."""
        section = path[len("/debug/"):]
        if section == "profile":
            await self._handle_profile(writer, query, head_only)
            return
        state = self.service.debug_state(section)
        if state is None:
            await self._send_json(writer, 404,
                                  error_body(f"unknown route {path!r}"))
            return
        await self._send_json(writer, 200, {section: state}
                              if isinstance(state, list) else state,
                              head_only=head_only)

    async def _handle_profile(self, writer, query: str,
                              head_only=False) -> None:
        """On-demand `jax.profiler` capture (ISSUE 11): record an XLA
        trace of whatever the engine is doing for `duration_s` seconds
        and answer with the logdir. The engine keeps serving — its drive
        loop shares this event loop, so the captured window IS live
        traffic. One capture at a time: jax has a single global tracer,
        so a concurrent request answers 409 instead of crashing it."""
        if head_only:
            # the one debug route with a side effect: a HEAD probe must
            # not start a 1-60s capture (nor burn the one-at-a-time
            # slot, nor litter tempdirs) — 405, not GET-minus-body
            await self._send_json(writer, 405, error_body(
                "HEAD not allowed on /debug/profile; use GET"))
            return
        import urllib.parse

        params = urllib.parse.parse_qs(query)
        try:
            duration = float(params.get("duration_s", ["1.0"])[0])
        except ValueError:
            await self._send_json(writer, 400, error_body(
                f"bad duration_s {params.get('duration_s')!r}"))
            return
        if not 0.0 < duration <= 60.0:
            await self._send_json(writer, 400, error_body(
                f"duration_s must be in (0, 60], got {duration}"))
            return
        if self._profiling:
            # busy check BEFORE any side effect: a 409'd request must
            # not litter a tempdir per rejected poll
            await self._send_json(writer, 409, error_body(
                "a profiler capture is already running (jax has one "
                "global tracer)", "conflict"))
            return
        logdir = params.get("logdir", [None])[0]
        auto_dir = logdir is None
        if auto_dir:
            import tempfile

            logdir = tempfile.mkdtemp(prefix="accelerate-tpu-profile-")
        self._profiling = True
        from ..profiler import profile as _profile

        try:
            with _profile(logdir):
                await asyncio.sleep(duration)
        except Exception as e:
            if auto_dir:
                import shutil

                shutil.rmtree(logdir, ignore_errors=True)
            await self._send_json(writer, 500, error_body(
                f"profiler capture failed: {type(e).__name__}: {e}",
                "server_error"))
            return
        finally:
            self._profiling = False
        await self._send_json(writer, 200, {"profile": {
            "logdir": logdir, "duration_s": duration,
        }}, head_only=head_only)

    # -- generation ----------------------------------------------------------

    async def _handle_generate(self, writer, path: str, headers: dict,
                               body: bytes) -> None:
        chat = path.endswith("/chat/completions")
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{next(self._req_ids)}"
        created = int(time.time())
        # trace context: honor a VALID inbound W3C traceparent (the
        # request joins the caller's distributed trace), mint fresh on
        # anything else — malformed headers are ignored, never an error.
        # The id exists for every generate request, sampled or not, and
        # rides EVERY response as x-request-id.
        parsed_tp = parse_traceparent(headers.get("traceparent"))
        trace_id, trace_parent = parsed_tp or (new_trace_id(), 0)
        rid_hdr = {"x-request-id": trace_id}
        try:
            try:
                parsed = json.loads(body)
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ProtocolError(400, f"invalid JSON body: {e}")
            max_ctx = self.service.engine.engine_config.max_len
            params = (parse_chat_request if chat
                      else parse_completion_request)(
                parsed, max_ctx, self.config.default_max_tokens)
            tenant = self.service.resolve_tenant(
                headers.get("x-tenant"), params.user)
            reqs = self.service.submit(params, tenant, trace_id=trace_id,
                                       trace_parent=trace_parent)
        except OverloadedError as e:
            await self._send_json(
                writer, e.status, self._with_request_id(e.body(), trace_id),
                extra=self._retry_after(e.retry_after_s, rid_hdr))
            return
        except ProtocolError as e:
            await self._send_json(writer, e.status,
                                  self._with_request_id(e.body(), trace_id),
                                  extra=rid_hdr)
            return
        model = self.config.model_id
        try:
            if params.stream:
                await self._stream_response(writer, rid, model, created,
                                            params, reqs, chat, rid_hdr)
            else:
                await self._unary_response(writer, rid, model, created,
                                           params, reqs, chat, rid_hdr)
        except OverloadedError as e:
            await self._send_json(
                writer, e.status, self._with_request_id(e.body(), trace_id),
                extra=self._retry_after(e.retry_after_s, rid_hdr))
        except ProtocolError as e:
            await self._send_json(writer, e.status,
                                  self._with_request_id(e.body(), trace_id),
                                  extra=rid_hdr)
        except ConnectionError:
            # the client went away mid-generation: release the slots and
            # pages its requests were holding — other tenants are queued
            self.service.cancel(reqs)

    @staticmethod
    def _with_request_id(body: dict, trace_id: str) -> dict:
        """The trace id INSIDE the error envelope too: SSE error events
        and proxied responses often lose response headers, and a 429
        must stay attributable to its trace either way."""
        if "error" in body:
            body["error"]["request_id"] = trace_id
        return body

    @staticmethod
    def _retry_after(retry_after_s: float | None,
                     base: dict | None = None) -> dict:
        out = dict(base or {})
        if retry_after_s is not None:
            out["Retry-After"] = f"{max(retry_after_s, 0.05):.3f}"
        return out

    def _rank(self, params, reqs):
        """best_of ranking by TRUE cumulative logprob (the engine emits
        each token's model logprob — ISSUE 12): highest sum of emitted-
        token logprobs wins, ties to the lower candidate index. A
        candidate with no logprobs (shed before any token) ranks last."""
        if params.best_of <= params.n:
            return reqs
        order = sorted(
            range(len(reqs)),
            key=lambda i: (-(reqs[i].cumulative_logprob
                             if reqs[i].cumulative_logprob is not None
                             else float("-inf")), i))
        return [reqs[i] for i in order[:params.n]]

    async def _unary_response(self, writer, rid, model, created, params,
                              reqs, chat: bool,
                              rid_hdr: dict | None = None) -> None:
        await self.service.wait_all(reqs)
        chosen = self._rank(params, reqs)
        tokenizer = self.service.tokenizer
        choices = []
        prompt_tokens = chosen[0].prompt_len if chosen else 0
        completion_tokens = 0
        for idx, req in enumerate(chosen):
            choice = _Choice(tokenizer, params.stop)
            choice.push(list(req.tokens))
            choice.finish()
            completion_tokens += len(req.tokens)
            reason = "stop" if choice.stopped \
                else self.service.finish_reason(req)
            text = choice.text
            if params.echo and not chat:
                text = tokenizer.decode(list(req.prompt)) + text
            lp_block = None
            if params.logprobs is not None:
                lp_block = logprobs_block(req.tokens, req.logprobs)
            if chat:
                entry = {
                    "index": idx,
                    "message": {"role": "assistant", "content": text,
                                "token_ids": choice.token_ids},
                    "finish_reason": reason}
                if lp_block is not None:
                    entry["logprobs"] = lp_block
                choices.append(entry)
            else:
                choices.append({
                    "index": idx, "text": text,
                    "token_ids": choice.token_ids,
                    "logprobs": lp_block, "finish_reason": reason})
        build = chat_response if chat else completion_response
        await self._send_json(
            writer, 200,
            build(rid, model, created, choices,
                  usage_block(prompt_tokens, completion_tokens)),
            extra=rid_hdr)

    async def _stream_response(self, writer, rid, model, created, params,
                               reqs, chat: bool,
                               rid_hdr: dict | None = None) -> None:
        # hold the 200 until something real exists to stream: a request
        # shed from the queue BEFORE its first token still gets a clean
        # 429 + Retry-After (the overload contract must not depend on
        # whether the client asked to stream)
        await self.service.await_first(reqs)
        await self._send_head(writer, 200, "text/event-stream",
                              {"Cache-Control": "no-cache",
                               **(rid_hdr or {})})
        make = chat_chunk if chat else completion_chunk
        choices = [_Choice(self.service.tokenizer, params.stop)
                   for _ in reqs]
        first = [True] * len(reqs)
        try:
            async for idx, ids, lps, done in self.service.stream_tokens(reqs):
                ch = choices[idx]
                lp_block = (logprobs_block(ids, lps)
                            if params.logprobs is not None else None)
                if done:
                    delta = ch.finish()
                    reason = "stop" if ch.stopped \
                        else self.service.finish_reason(reqs[idx])
                    payload = make(rid, model, created, idx, delta, [],
                                   reason, **({"first": first[idx]}
                                              if chat else {}),
                                   **({"logprobs": logprobs_block([], [])}
                                      if params.logprobs is not None
                                      else {}))
                elif ch.stopped:
                    continue  # stop string hit earlier; suppress the tail
                else:
                    delta = ch.push(ids)
                    if ch.stopped:
                        # the answer is complete: retire as FINISHED so
                        # stream and unary stop-hits count identically
                        self.service.finish(reqs[idx])
                    payload = make(rid, model, created, idx, delta, ids,
                                   None, **({"first": first[idx]}
                                            if chat else {}),
                                   **({"logprobs": lp_block}
                                      if lp_block is not None else {}))
                first[idx] = False
                writer.write(sse_event(payload))
                # drain() is where a dead client surfaces: the
                # ConnectionError propagates to _handle_generate, which
                # cancels every request of this stream
                await writer.drain()
            writer.write(SSE_DONE)
            await writer.drain()
        except ProtocolError as e:
            # the SSE head is already on the wire, so a late failure
            # (engine drive death, mid-wait shed) becomes a terminal SSE
            # error event — never a second HTTP status line mid-stream
            self.service.cancel(reqs)
            body = e.body()
            if rid_hdr:
                body = self._with_request_id(body,
                                             rid_hdr["x-request-id"])
            writer.write(sse_event(body))
            writer.write(SSE_DONE)
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError) as e:
            raise ConnectionError(str(e)) from e
