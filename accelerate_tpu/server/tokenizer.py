"""Tokenizer plumbing: prompt -> ids at the door, ids -> text deltas out.

The model zoo's research configs carry no trained tokenizer, and the
container policy forbids pulling one in — so the server ships two
self-contained codecs and a protocol any external tokenizer can slot
into (`encode`/`decode`/`eos_token_id`):

- `ByteTokenizer`: UTF-8 bytes ARE the token ids (0..255). Lossless for
  any text, needs vocab >= 256, and — the part that matters for SSE —
  decodes *incrementally*: a multi-byte character whose bytes land in
  different decode steps is held back until complete, so no stream event
  ever carries a torn code point.
- `NumericTokenizer`: for vocabularies smaller than 256 (the tiny test
  configs). Prompts must arrive as token-id arrays (the OpenAI `prompt`
  field accepts arrays); output renders each id as its decimal string
  plus a space — deterministic, reversible, and honest about the absence
  of a text mapping.

Both are pure host-side Python; nothing here touches jax.
"""

from __future__ import annotations

import codecs

__all__ = ["ByteTokenizer", "NumericTokenizer", "IncrementalDetokenizer",
           "get_tokenizer"]


class ByteTokenizer:
    """UTF-8 byte-level codec: token id == byte value."""

    name = "byte"

    def __init__(self, vocab_size: int, eos_token_id: int | None = None):
        if vocab_size < 256:
            raise ValueError(
                f"byte tokenizer needs vocab_size >= 256, got {vocab_size} "
                "(use the numeric tokenizer for tiny vocabularies)")
        self.vocab_size = vocab_size
        self.eos_token_id = eos_token_id

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if 0 <= i <= 255).decode(
            "utf-8", errors="replace")

    def incremental(self) -> "IncrementalDetokenizer":
        return _ByteIncremental()


class NumericTokenizer:
    """Decimal rendering for models with no text mapping at all."""

    name = "numeric"

    def __init__(self, vocab_size: int, eos_token_id: int | None = None):
        self.vocab_size = vocab_size
        self.eos_token_id = eos_token_id

    def encode(self, text: str) -> list[int]:
        # text prompts are parseable only if they look like our own
        # decode output ("12 7 300 "); anything else is a client error
        try:
            ids = [int(t) for t in text.split()]
        except ValueError:
            raise ValueError(
                "this model has no text tokenizer: send 'prompt' as an "
                "array of token ids (or space-separated decimal ids)")
        if not ids:
            raise ValueError("empty prompt")
        return ids

    def decode(self, ids: list[int]) -> str:
        return "".join(f"{i} " for i in ids)

    def incremental(self) -> "IncrementalDetokenizer":
        return _NumericIncremental()


class IncrementalDetokenizer:
    """Streaming ids -> text: `push(ids)` returns the text newly
    *complete* at this step (possibly ""), `flush()` drains any tail."""

    def push(self, ids: list[int]) -> str:  # pragma: no cover - interface
        raise NotImplementedError

    def flush(self) -> str:
        return ""


class _ByteIncremental(IncrementalDetokenizer):
    def __init__(self):
        self._dec = codecs.getincrementaldecoder("utf-8")(errors="replace")

    def push(self, ids: list[int]) -> str:
        return self._dec.decode(bytes(i for i in ids if 0 <= i <= 255))

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)


class _NumericIncremental(IncrementalDetokenizer):
    def push(self, ids: list[int]) -> str:
        return "".join(f"{i} " for i in ids)


def get_tokenizer(name: str, vocab_size: int,
                  eos_token_id: int | None = None):
    """Resolve a tokenizer by name; "auto" picks byte when the vocabulary
    can hold it, numeric otherwise."""
    if name == "auto":
        name = "byte" if vocab_size >= 256 else "numeric"
    if name == "byte":
        return ByteTokenizer(vocab_size, eos_token_id)
    if name == "numeric":
        return NumericTokenizer(vocab_size, eos_token_id)
    raise ValueError(f"unknown tokenizer {name!r} (byte|numeric|auto)")
