"""OpenAI-compatible HTTP front door for the serving engine.

The engine (`accelerate_tpu.serving`) speaks Python; production traffic
speaks HTTP. This package is the user-facing layer over it, stdlib-only
(asyncio streams — no web framework dependency), in five pieces:

- `protocol`:  request validation + OpenAI response/error envelopes + SSE
               framing, jax-free and server-free so it unit-tests in
               microseconds;
- `tokenizer`: prompt -> token ids in, token ids -> text deltas out
               (byte-level UTF-8 tokenizer for real text, numeric
               fallback for tiny research vocabularies, incremental
               decoding so multi-byte characters never split across SSE
               events);
- `service`:   the asyncio glue — one background drive task steps the
               engine, watchers stream tokens per request, n/best_of
               fan-out, graceful drain, health;
- `http`:      the HTTP/1.1 layer — routing (/v1/completions,
               /v1/chat/completions, /v1/models, /healthz, /metrics),
               SSE streaming, client-disconnect cancellation, 429 +
               Retry-After on shed, graceful shutdown;
- `config`:    ServerConfig + tenant-spec parsing shared by the CLI and
               the load harness.

`accelerate-tpu serve` (commands/serve.py) is the CLI entry;
benchmarks/serve_bench.py drives the real endpoint for the offered-load
proof. See docs/server.md.
"""

from .config import ServerConfig, parse_tenants_arg
from .http import HttpFrontDoor
from .service import InferenceService
from .tokenizer import ByteTokenizer, NumericTokenizer, get_tokenizer

__all__ = [
    "ServerConfig",
    "parse_tenants_arg",
    "HttpFrontDoor",
    "InferenceService",
    "ByteTokenizer",
    "NumericTokenizer",
    "get_tokenizer",
]
