"""OpenAI wire protocol: request validation, response envelopes, SSE.

jax-free and socket-free on purpose: everything here maps dicts to dicts
(plus SSE byte framing), so the protocol contract unit-tests without a
model, an engine, or a listening port. Validation errors raise
`ProtocolError` carrying the HTTP status and the OpenAI error envelope —
the HTTP layer turns them into structured 4xx responses WITHOUT touching
the scheduler (a malformed request must never cost the data plane
anything).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

__all__ = [
    "ProtocolError",
    "CompletionParams",
    "parse_completion_request",
    "parse_chat_request",
    "completion_chunk",
    "completion_response",
    "chat_chunk",
    "chat_response",
    "error_body",
    "logprobs_block",
    "sse_event",
    "SSE_DONE",
]

# one request body is bounded (prompts are tokens, not megabytes); the
# HTTP layer enforces this before json.loads so a hostile body never
# allocates unbounded memory
MAX_BODY_BYTES_DEFAULT = 2 * 1024 * 1024


class ProtocolError(Exception):
    """Invalid request, mapped straight to an HTTP status + OpenAI error
    envelope (`{"error": {message, type, code}}`)."""

    def __init__(self, status: int, message: str, etype: str = "invalid_request_error",
                 code: str | None = None):
        super().__init__(message)
        self.status = status
        self.etype = etype
        self.code = code

    def body(self) -> dict:
        return error_body(self.args[0], self.etype, self.code)


def error_body(message: str, etype: str = "invalid_request_error",
               code: str | None = None) -> dict:
    return {"error": {"message": message, "type": etype, "code": code}}


@dataclasses.dataclass
class CompletionParams:
    """One validated generation request, engine-shaped: the prompt is
    either text (tokenizer encodes it) or already token ids."""

    prompt_text: str | None
    prompt_ids: list[int] | None
    max_tokens: int
    temperature: float
    n: int
    best_of: int
    stream: bool
    echo: bool
    stop: list[str]
    user: str | None
    seed: int | None
    chat: bool = False
    # OpenAI `logprobs`: None = off; 0/1 = include each emitted token's
    # model logprob (the engine computes exactly one logprob per token —
    # the emitted one — so top-N alternatives beyond 1 are rejected at
    # validation, not silently dropped). Chat's boolean `logprobs` maps
    # to 0. See docs/server.md for the response-block shape.
    logprobs: int | None = None

    @property
    def fan_out(self) -> int:
        """Engine requests this API request expands to."""
        return max(self.n, self.best_of)


def _require_dict(body: Any) -> dict:
    if not isinstance(body, dict):
        raise ProtocolError(400, "request body must be a JSON object")
    return body


def _int_field(body: dict, name: str, default: int, lo: int, hi: int) -> int:
    v = body.get(name, default)
    if v is None:
        v = default
    if isinstance(v, bool) or not isinstance(v, int):
        raise ProtocolError(400, f"'{name}' must be an integer")
    if not lo <= v <= hi:
        raise ProtocolError(400, f"'{name}' must be in [{lo}, {hi}], got {v}")
    return v


def _float_field(body: dict, name: str, default: float, lo: float,
                 hi: float) -> float:
    v = body.get(name, default)
    if v is None:
        v = default
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ProtocolError(400, f"'{name}' must be a number")
    if not lo <= v <= hi:
        raise ProtocolError(400, f"'{name}' must be in [{lo}, {hi}], got {v}")
    return float(v)


def _parse_prompt(raw: Any) -> tuple[str | None, list[int] | None]:
    """OpenAI accepts a string or an array of token ids (arrays of
    strings/arrays — batch prompts — are deliberately unsupported: the
    engine-side fan-out is `n`, not prompt batching)."""
    if isinstance(raw, str):
        if not raw:
            raise ProtocolError(400, "'prompt' must not be empty")
        return raw, None
    if isinstance(raw, list):
        if not raw:
            raise ProtocolError(400, "'prompt' must not be empty")
        if not all(isinstance(t, int) and not isinstance(t, bool)
                   and t >= 0 for t in raw):
            raise ProtocolError(
                400, "'prompt' array must contain nonnegative token ids")
        return None, list(raw)
    raise ProtocolError(
        400, "'prompt' must be a string or an array of token ids")


def _parse_stop(body: dict) -> list[str]:
    raw = body.get("stop")
    if raw is None:
        return []
    if isinstance(raw, str):
        return [raw]
    if (isinstance(raw, list) and len(raw) <= 4
            and all(isinstance(s, str) and s for s in raw)):
        return list(raw)
    raise ProtocolError(400, "'stop' must be a string or up to 4 strings")


def _parse_common(body: dict, max_total_tokens: int,
                  default_max_tokens: int) -> dict:
    max_tokens = _int_field(body, "max_tokens", default_max_tokens, 1,
                            max_total_tokens)
    temperature = _float_field(body, "temperature", 1.0, 0.0, 2.0)
    n = _int_field(body, "n", 1, 1, 16)
    best_of = _int_field(body, "best_of", n, 1, 16)
    if best_of < n:
        raise ProtocolError(400, f"'best_of' ({best_of}) must be >= 'n' ({n})")
    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        raise ProtocolError(400, "'stream' must be a boolean")
    if stream and best_of > n:
        # OpenAI semantics: best_of needs all candidates complete before
        # ranking, which contradicts streaming the winner live
        raise ProtocolError(400, "'best_of' > 'n' cannot be streamed")
    seed = body.get("seed")
    if seed is not None and (isinstance(seed, bool)
                             or not isinstance(seed, int)):
        raise ProtocolError(400, "'seed' must be an integer")
    user = body.get("user")
    if user is not None and not isinstance(user, str):
        raise ProtocolError(400, "'user' must be a string")
    return dict(max_tokens=max_tokens, temperature=temperature, n=n,
                best_of=best_of, stream=stream, stop=_parse_stop(body),
                user=user, seed=seed)


def _parse_logprobs(body: dict, chat: bool) -> int | None:
    """Completions take an int (0/1 supported — the engine has exactly
    the emitted token's logprob, so requests for top-N alternatives are
    a 400, not silent truncation); chat takes the OpenAI boolean."""
    raw = body.get("logprobs")
    if raw is None or raw is False:
        return None
    if chat:
        if raw is True:
            return 0
        raise ProtocolError(400, "'logprobs' must be a boolean for chat")
    if isinstance(raw, bool) or not isinstance(raw, int):
        raise ProtocolError(400, "'logprobs' must be an integer")
    if not 0 <= raw <= 1:
        raise ProtocolError(
            400, f"'logprobs' must be 0 or 1, got {raw}: the server "
            "returns the emitted token's logprob only (top-N "
            "alternatives are not computed)")
    return raw


def parse_completion_request(body: Any, max_total_tokens: int,
                             default_max_tokens: int = 16) -> CompletionParams:
    """Validate a `/v1/completions` body into CompletionParams; raises
    ProtocolError(4xx) on anything malformed."""
    body = _require_dict(body)
    if "prompt" not in body:
        raise ProtocolError(400, "'prompt' is required")
    text, ids = _parse_prompt(body["prompt"])
    echo = body.get("echo", False)
    if not isinstance(echo, bool):
        raise ProtocolError(400, "'echo' must be a boolean")
    return CompletionParams(prompt_text=text, prompt_ids=ids, echo=echo,
                            chat=False,
                            logprobs=_parse_logprobs(body, chat=False),
                            **_parse_common(body, max_total_tokens,
                                            default_max_tokens))


def render_chat_prompt(messages: list[dict]) -> str:
    """Deterministic chat template: the tiny research families have no
    trained template, so the server uses a fixed readable one — what
    matters for the serving layer is that identical messages always
    yield identical token streams."""
    parts = [f"<|{m['role']}|>\n{m['content']}\n" for m in messages]
    parts.append("<|assistant|>\n")
    return "".join(parts)


def parse_chat_request(body: Any, max_total_tokens: int,
                       default_max_tokens: int = 16) -> CompletionParams:
    """Validate a `/v1/chat/completions` body. Messages render through
    the fixed chat template into one prompt string."""
    body = _require_dict(body)
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise ProtocolError(400, "'messages' must be a non-empty array")
    for m in messages:
        if (not isinstance(m, dict)
                or not isinstance(m.get("role"), str)
                or m["role"] not in ("system", "user", "assistant", "tool")
                or not isinstance(m.get("content"), str)):
            raise ProtocolError(
                400, "each message needs a role "
                "(system|user|assistant|tool) and string content")
    common = _parse_common(body, max_total_tokens, default_max_tokens)
    if common["best_of"] > common["n"]:
        raise ProtocolError(400, "'best_of' is not supported for chat")
    return CompletionParams(prompt_text=render_chat_prompt(messages),
                            prompt_ids=None, echo=False, chat=True,
                            logprobs=_parse_logprobs(body, chat=True),
                            **common)


# -- response envelopes ------------------------------------------------------


def _base(kind: str, rid: str, model: str, created: int) -> dict:
    return {"id": rid, "object": kind, "created": created, "model": model}


def completion_response(rid: str, model: str, created: int,
                        choices: list[dict], usage: dict) -> dict:
    out = _base("text_completion", rid, model, created)
    out["choices"] = choices
    out["usage"] = usage
    return out


def logprobs_block(token_ids: list[int],
                   token_logprobs: list[float]) -> dict:
    """The `logprobs` choice field: per-token model logprobs of the
    emitted tokens (log-softmax of the raw target logits — temperature-
    free). Deviation from OpenAI, documented in docs/server.md: tokens
    are identified by `token_ids`, not decoded strings (the byte-level
    tokenizer's single tokens need not be valid code points), and
    `top_logprobs` is always null (only the emitted token's logprob is
    computed)."""
    return {
        "token_ids": list(token_ids),
        "token_logprobs": [round(float(lp), 6) for lp in token_logprobs],
        "top_logprobs": None,
    }


def completion_chunk(rid: str, model: str, created: int, index: int,
                     text: str, token_ids: list[int],
                     finish_reason: str | None,
                     logprobs: dict | None = None) -> dict:
    out = _base("text_completion", rid, model, created)
    # `token_ids` is an extension field: it makes streamed output
    # byte-auditable against Engine.stream (the acceptance contract) and
    # lets id-level clients skip detokenization entirely
    out["choices"] = [{"index": index, "text": text, "token_ids": token_ids,
                       "logprobs": logprobs,
                       "finish_reason": finish_reason}]
    return out


def chat_response(rid: str, model: str, created: int,
                  choices: list[dict], usage: dict) -> dict:
    out = _base("chat.completion", rid, model, created)
    out["choices"] = choices
    out["usage"] = usage
    return out


def chat_chunk(rid: str, model: str, created: int, index: int, text: str,
               token_ids: list[int], finish_reason: str | None,
               first: bool = False, logprobs: dict | None = None) -> dict:
    out = _base("chat.completion.chunk", rid, model, created)
    delta: dict = {"content": text, "token_ids": token_ids}
    if first:
        delta["role"] = "assistant"
    choice = {"index": index, "delta": delta,
              "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    out["choices"] = [choice]
    return out


def usage_block(prompt_tokens: int, completion_tokens: int) -> dict:
    return {"prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens}


# -- SSE framing -------------------------------------------------------------

SSE_DONE = b"data: [DONE]\n\n"


def sse_event(payload: dict) -> bytes:
    """One server-sent event frame carrying a JSON payload."""
    return b"data: " + json.dumps(payload, separators=(",", ":")).encode() \
        + b"\n\n"
