"""Asyncio glue between the HTTP layer and the serving engine.

One background *drive task* steps the engine whenever it has work — the
engine is not thread-safe and its step() is a quick host dispatch, so
stepping inline on the event loop (yielding between steps) keeps every
device interaction on one logical thread while any number of request
coroutines watch their tokens land. Watchers never call step()
themselves: they await a progress future the drive task resolves after
every engine step, which is what lets a client disconnect cancel ONE
request (freeing its slot and pages immediately) without perturbing the
others.

Fan-out (`n`/`best_of`) is ONE engine submission plus N-1 `Engine.fork`s
(ISSUE 12): siblings share the parent's prompt pages copy-on-write
through the radix tree — published as the parent's prefill completes
them, so the whole fan-out pays a single prompt prefill and each sibling
diverges at its first private page. best_of ranks finished candidates by
TRUE cumulative logprob (the engine emits per-token model logprobs),
ties to the lower candidate index. Engines without `fork` (the pod
router) fall back to independent submissions — sharing then happens
only through ordinary retirement-time prefix reuse.

Graceful drain: `drain()` flips the service to draining (healthz -> 503,
new submissions -> 503), lets in-flight requests finish inside the
timeout, then cancels the stragglers — the front door never vanishes
mid-stream.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator

import numpy as np

from ..serving.scheduler import Request, RequestStatus
from .config import ServerConfig
from .protocol import ProtocolError

__all__ = ["InferenceService", "OverloadedError"]


class OverloadedError(ProtocolError):
    """429 + Retry-After: the scheduler shed or refused the request.
    `shed_code` is the scheduler's machine-readable reason (certain_miss,
    pressure_victim, displaced_by_tier, queue_full, ...) — it rides the
    envelope as `error.shed_reason` so a client or load balancer can
    react to WHY it was shed, not just that it was."""

    def __init__(self, message: str, retry_after_s: float | None,
                 shed_code: str | None = None):
        super().__init__(429, message, etype="overloaded_error",
                         code="rate_limit_exceeded")
        self.retry_after_s = retry_after_s
        self.shed_code = shed_code

    def body(self) -> dict:
        out = super().body()
        if self.shed_code is not None:
            out["error"]["shed_reason"] = self.shed_code
        return out


class InferenceService:
    """Owns the engine drive loop + request watching for the HTTP layer."""

    def __init__(self, engine, tokenizer, config: ServerConfig | None = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.config = config or ServerConfig()
        self._known = {t.name for t in self.config.tenants}
        self._known.add("default")
        self.draining = False
        self._wake: asyncio.Event | None = None
        self._progress_waiters: list[asyncio.Future] = []
        self._drive_task: asyncio.Task | None = None
        self._drive_error: BaseException | None = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._wake = asyncio.Event()
        self._drive_task = asyncio.get_running_loop().create_task(
            self._drive(), name="engine-drive")

    async def stop(self) -> None:
        await self.drain()
        if self._drive_task is not None:
            self._drive_task.cancel()
            try:
                await self._drive_task
            except asyncio.CancelledError:
                pass
            except BaseException:
                pass  # already recorded as _drive_error and surfaced
            self._drive_task = None
        # engine.close() joins the watchdog / metrics-server / host-tier
        # threads — seconds of blocking if one is mid-drain. The drive
        # task is already dead, so no engine call races this; run it off
        # the loop so health checks and other servers on this loop keep
        # answering while we tear down. (ATP303's module-local view ends
        # at the engine boundary; this is the audit fix it points at.)
        await asyncio.get_running_loop().run_in_executor(
            None, self.engine.close)

    async def drain(self, timeout_s: float | None = None) -> None:
        """Stop admitting, let in-flight work finish, cancel stragglers."""
        self.draining = True
        timeout = (self.config.drain_timeout_s
                   if timeout_s is None else timeout_s)
        deadline = time.monotonic() + timeout
        while (self.engine.scheduler.has_work()
               and time.monotonic() < deadline):
            await asyncio.sleep(0.01)
        for req in list(self.engine.scheduler.queue):
            self.engine.cancel(req)
        for req in list(self.engine.scheduler.running()):
            self.engine.cancel(req)
        self._notify_progress()  # release any watcher still waiting

    def health(self) -> tuple[bool, str]:
        """(ok, reason). Degrades on drain and on a fired stall watchdog
        — a wedged engine must fail its readiness probe, not serve 200s
        over a queue nothing is draining."""
        if self._drive_error is not None:
            return False, ("engine drive loop failed: "
                           f"{type(self._drive_error).__name__}")
        if self.draining:
            return False, "draining"
        wd = self.engine.watchdog
        if wd is not None and wd.stalled:
            return False, (f"stall watchdog fired ({wd.stall_count} "
                           f"stall(s), last silence > {wd.timeout_s}s)")
        return True, "ok"

    def debug_state(self, section: str) -> dict | list | None:
        """Introspection snapshot for one /debug/<section> route; None
        for an unknown section (the HTTP layer 404s). Service-level
        health rides along on `requests` so one fetch answers 'is the
        loop alive AND what is it holding'."""
        if section == "requests":
            out = self.engine.debug_requests()
            ok, reason = self.health()
            out["service"] = {"healthy": ok, "reason": reason,
                              "draining": self.draining}
            return out
        if section == "slots":
            return self.engine.debug_slots()
        if section == "pages":
            return self.engine.debug_pages()
        if section == "scheduler":
            return self.engine.debug_scheduler()
        if section == "pod":
            # only a pod-backed engine (serving.pod.PodEngine) has role/
            # router state; on a single engine the route 404s like any
            # unknown section
            build = getattr(self.engine, "debug_pod", None)
            return build() if build is not None else None
        return None

    # -- the drive loop ------------------------------------------------------

    async def _drive(self) -> None:
        try:
            while True:
                if self.engine.scheduler.has_work():
                    self.engine.step()
                    self._notify_progress()
                    # yield so watchers flush tokens between steps
                    await asyncio.sleep(0)
                else:
                    self._notify_progress()
                    self._wake.clear()
                    wd = self.engine.watchdog
                    if wd is None:
                        await self._wake.wait()
                    else:
                        # idle is progress, not a stall: the watchdog is
                        # normally ticked inside Engine.step(), so an
                        # armed watchdog on a traffic-less server would
                        # fire and fail /healthz forever — keep ticking
                        # on a sub-timeout period while waiting for work
                        wd.tick()
                        try:
                            await asyncio.wait_for(
                                self._wake.wait(),
                                timeout=max(0.05, wd.timeout_s / 2.0))
                        except asyncio.TimeoutError:
                            pass
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            # a dead drive loop must FAIL every request, not hang it:
            # record the error (watchers re-raise it as a 500), refuse
            # new work, cancel everything in flight, wake all waiters —
            # and leave an incident bundle behind (the drive loop dying
            # IS the incident the stall watchdog exists for, just loud)
            self._drive_error = e
            self._write_incident(e)
            self.draining = True
            for req in list(self.engine.scheduler.queue):
                self.engine.cancel(req)
            for req in list(self.engine.scheduler.running()):
                self.engine.cancel(req)
            self._notify_progress()
            raise

    def _write_incident(self, exc: BaseException) -> None:
        """Best-effort drive-death bundle: same format as the watchdog's
        stall bundles, kind 'drive-loop', with the exception traceback
        and the engine's scheduler/slot/page dumps frozen at death."""
        try:
            from ..telemetry.watchdog import (
                build_exception_report,
                resolve_incident_dir,
                write_incident_bundle,
            )

            incident_dir = resolve_incident_dir(
                getattr(self.engine.engine_config, "incident_dir", None))
            if incident_dir is None:
                return
            report = build_exception_report(exc, name="drive-loop")
            path = write_incident_bundle(
                incident_dir, report, registry=self.engine.registry,
                dumps=self.engine.incident_dumps(), name="drive-loop")
            from ..logging import get_logger

            get_logger(__name__).error(
                f"engine drive loop died ({type(exc).__name__}); incident "
                f"bundle written: {path} (accelerate-tpu incident show)")
        except Exception:
            pass  # forensics must never mask the original failure

    def _notify_progress(self) -> None:
        waiters, self._progress_waiters = self._progress_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def _check_drive(self) -> None:
        if self._drive_error is not None:
            raise ProtocolError(
                500, "engine drive loop failed: "
                f"{type(self._drive_error).__name__}: {self._drive_error}",
                etype="server_error", code="engine_failure")

    async def _wait_progress(self) -> None:
        self._check_drive()
        fut = asyncio.get_running_loop().create_future()
        self._progress_waiters.append(fut)
        await fut
        self._check_drive()

    # -- tenancy -------------------------------------------------------------

    def resolve_tenant(self, header: str | None, user: str | None) -> str:
        """`X-Tenant` header wins, then the OpenAI `user` field. Unknown
        names 401 in `unknown_tenants="reject"` deployments (a typo'd
        tenant silently riding the default tier corrupts per-tier SLO
        accounting), else serve under a default-shaped contract."""
        tenant = header or user or "default"
        if (tenant not in self._known
                and self.config.unknown_tenants == "reject"):
            raise ProtocolError(401, f"unknown tenant {tenant!r}",
                                etype="authentication_error",
                                code="unknown_tenant")
        return tenant

    # -- submission ----------------------------------------------------------

    def encode_prompt(self, params) -> list[int]:
        if params.prompt_ids is not None:
            bad = [t for t in params.prompt_ids
                   if t >= self.tokenizer.vocab_size]
            if bad:
                raise ProtocolError(
                    400, f"prompt token id {bad[0]} out of range for "
                    f"vocab_size {self.tokenizer.vocab_size}")
            return list(params.prompt_ids)
        try:
            return self.tokenizer.encode(params.prompt_text)
        except ValueError as e:
            raise ProtocolError(400, str(e))

    def submit(self, params, tenant: str, trace_id=None,
               trace_parent=0) -> list[Request]:
        """Validate capacity, then fan out `max(n, best_of)` engine
        requests. Oversized prompts 4xx HERE — the scheduler never sees
        them. Overload (scheduler REJECTED) raises OverloadedError with
        the scheduler's Retry-After estimate and shed code; partial
        fan-outs roll back so a shed request never leaks half its
        siblings. All candidates of one HTTP request share one trace —
        `trace_id` is the id the front door returns as `x-request-id`."""
        if self.draining:
            raise ProtocolError(503, "server is draining",
                                etype="overloaded_error", code="draining")
        ids = self.encode_prompt(params)
        max_len = self.engine.engine_config.max_len
        if len(ids) + params.max_tokens > max_len:
            raise ProtocolError(
                400, f"prompt ({len(ids)} tokens) + max_tokens "
                f"({params.max_tokens}) exceeds the model context "
                f"({max_len})", code="context_length_exceeded")
        prompt = np.asarray(ids, np.int32)
        # ONE head-sampling decision for the whole fan-out: n/best_of
        # siblings share the trace, so they must sample together — at a
        # fractional rate, per-candidate draws would leave a random
        # subset of a request's spans missing (half a trace is noise)
        from ..telemetry.trace import head_sample

        sampled = head_sample(tenant)
        # COW fan-out: candidate 0 submits normally, siblings FORK it —
        # they share its prompt pages (published as its prefill completes
        # them), so n=8 pays one prompt prefill. The pod router has no
        # fork yet; it keeps the independent-submission path.
        fork = getattr(self.engine, "fork", None)
        reqs: list[Request] = []
        for i in range(params.fan_out):
            key = None
            if params.seed is not None:
                # distinct deterministic stream per candidate: raw
                # uint32[2] key data, same shape Engine._as_raw_key takes
                key = np.array([params.seed & 0xFFFFFFFF, i], np.uint32)
            if reqs and fork is not None:
                req = fork(
                    reqs[0], max_new_tokens=params.max_tokens,
                    temperature=params.temperature, key=key,
                    trace_id=trace_id, trace_parent=trace_parent,
                    trace_sampled=sampled,
                )
            else:
                req = self.engine.submit(
                    prompt, max_new_tokens=params.max_tokens,
                    temperature=params.temperature, key=key,
                    eos_token_id=self.tokenizer.eos_token_id, tenant=tenant,
                    trace_id=trace_id, trace_parent=trace_parent,
                    trace_sampled=sampled,
                )
            if req.status is RequestStatus.REJECTED:
                for sib in reqs:
                    self.engine.cancel(sib)
                raise OverloadedError(
                    f"request shed: {req.reject_reason}", req.retry_after_s,
                    shed_code=req.shed_code)
            reqs.append(req)
        if self._wake is not None:
            self._wake.set()
        return reqs

    def cancel(self, reqs) -> None:
        for r in reqs if isinstance(reqs, (list, tuple)) else [reqs]:
            self.engine.cancel(r)

    def finish(self, req) -> None:
        """Stop-sequence termination: the client got its full answer, so
        the request retires as FINISHED (metrics and prefix cache treat
        it exactly like a natural completion)."""
        self.engine.finish(req)

    # -- consumption ---------------------------------------------------------

    @staticmethod
    def finish_reason(req: Request) -> str:
        if req.status is RequestStatus.EXPIRED:
            return "overloaded"
        if req.status is RequestStatus.CANCELLED:
            return "cancelled"
        if len(req.tokens) >= req.max_new_tokens:
            return "length"
        return "stop"

    async def wait_all(self, reqs: list[Request],
                       timeout_s: float | None = None) -> None:
        """Block until every request is terminal. An EXPIRED request
        (shed from the queue mid-wait) surfaces as OverloadedError — the
        client gets its 429 + Retry-After even after the body started
        life admitted."""
        timeout = (self.config.request_timeout_s
                   if timeout_s is None else timeout_s)
        deadline = time.monotonic() + timeout
        while not all(r.done for r in reqs):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.cancel(reqs)
                raise ProtocolError(504, "generation timed out",
                                    etype="server_error", code="timeout")
            # bounded wait: the deadline fires even if no progress
            # notification ever arrives
            try:
                await asyncio.wait_for(self._wait_progress(),
                                       timeout=min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
        shed = next((r for r in reqs
                     if r.status is RequestStatus.EXPIRED), None)
        if shed is not None:
            self.cancel(reqs)
            raise OverloadedError(f"request shed: {shed.reject_reason}",
                                  shed.retry_after_s,
                                  shed_code=shed.shed_code)

    async def await_first(self, reqs: list[Request],
                          timeout_s: float | None = None) -> None:
        """Block until every request has produced a token or gone
        terminal; a request shed before its first token surfaces as
        OverloadedError — the streaming path holds its 200 on this, so
        queue sheds answer 429 whether or not the client streams. The
        request timeout applies here exactly as on the unary path: a
        stream stuck queued past it gets a 504, never a held socket
        (overload is an answer, not a hang)."""
        timeout = (self.config.request_timeout_s
                   if timeout_s is None else timeout_s)
        deadline = time.monotonic() + timeout
        while not all(r.tokens or r.done for r in reqs):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.cancel(reqs)
                raise ProtocolError(504, "generation timed out in queue",
                                    etype="server_error", code="timeout")
            try:
                await asyncio.wait_for(self._wait_progress(),
                                       timeout=min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
        shed = next((r for r in reqs
                     if r.status is RequestStatus.EXPIRED
                     and not r.tokens), None)
        if shed is not None:
            self.cancel(reqs)
            raise OverloadedError(f"request shed: {shed.reject_reason}",
                                  shed.retry_after_s,
                                  shed_code=shed.shed_code)

    async def stream_tokens(
            self, reqs: list[Request],
    ) -> AsyncIterator[tuple[int, list[int], list[float], bool]]:
        """Merge N live requests into one (choice_index, new_token_ids,
        new_token_logprobs, finished) stream; `finished` fires exactly
        once per choice, after its last tokens. The logprob slice is
        index-aligned with the token slice (both come from the same
        engine step)."""
        sent = [0] * len(reqs)
        closed = [False] * len(reqs)
        while not all(closed):
            progressed = False
            for i, r in enumerate(reqs):
                if closed[i]:
                    continue
                if sent[i] < len(r.tokens):
                    new = list(r.tokens[sent[i]:])
                    lps = list(r.logprobs[sent[i]:sent[i] + len(new)])
                    sent[i] = len(r.tokens)
                    progressed = True
                    yield i, new, lps, False
                if r.done:
                    closed[i] = True
                    progressed = True
                    yield i, [], [], True
            if not progressed:
                await self._wait_progress()
