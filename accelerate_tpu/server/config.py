"""Server configuration shared by the CLI, the tests, and the harness.

`ServerConfig` is the front-door half of the knobs (bind address, model
id, tokenizer, tenancy, drain); engine capacity lives in
`serving.EngineConfig` — the CLI builds both. Tenant specs parse from
the compact flag grammar used everywhere a human types them::

    gold:priority=0,weight=4,slo=0.25;bronze:priority=1,weight=1

(semicolon-separated tenants, each `name:key=value,...`; `slo` is the
TTFT objective in seconds, `max_queue` the per-tenant queue cap).
"""

from __future__ import annotations

import dataclasses

from ..serving.scheduler import TenantSpec

__all__ = ["ServerConfig", "parse_tenants_arg", "format_tenants"]


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 8000
    model_id: str = "accelerate-tpu"
    tokenizer: str = "auto"          # byte | numeric | auto
    tenants: tuple[TenantSpec, ...] = ()
    # tenants the scheduler has no spec for: "default" serves them under
    # a default-shaped contract, "reject" turns them into 401s at the
    # door (multi-tenant deployments want reject — a typo'd tenant name
    # silently riding the default tier is an SLO accounting leak)
    unknown_tenants: str = "default"
    default_max_tokens: int = 16
    max_body_bytes: int = 2 * 1024 * 1024
    drain_timeout_s: float = 30.0
    request_timeout_s: float = 300.0
    # read-only live-introspection routes (/debug/requests, /debug/slots,
    # /debug/pages, /debug/scheduler, and /debug/pod on a pod-backed
    # engine). Off by default: they expose
    # workload shape (tenants, queue depths, prompt lengths) and belong
    # behind the same trust boundary as /metrics, which an operator must
    # opt into explicitly.
    debug_endpoints: bool = False

    def __post_init__(self):
        if self.unknown_tenants not in ("default", "reject"):
            raise ValueError(
                "unknown_tenants must be 'default' or 'reject', got "
                f"{self.unknown_tenants!r}")


_KEYS = {"priority": int, "weight": float, "slo": float, "max_queue": int}


def parse_tenants_arg(arg: str | None, extra_keys: dict | None = None):
    """`gold:priority=0,weight=4,slo=0.25;bronze:weight=1` -> TenantSpecs.
    Empty/None -> () (single default tenant, FIFO).

    `extra_keys` ({name: type}) admits caller-owned fields on top of the
    TenantSpec ones (the load harness adds `rate`/`concurrency`); the
    call then returns `(specs, {tenant: {extra...}})` instead of specs
    alone."""
    if not arg:
        return ((), {}) if extra_keys else ()
    keys = dict(_KEYS, **(extra_keys or {}))
    specs, extras = [], {}
    for chunk in arg.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, _, rest = chunk.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant spec {chunk!r}: empty name")
        kwargs: dict = {}
        extra: dict = {}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            key, eq, val = pair.partition("=")
            key = key.strip()
            if not eq or key not in keys:
                raise ValueError(
                    f"tenant spec {name!r}: bad field {pair!r} "
                    f"(known: {', '.join(keys)})")
            try:
                parsed = keys[key](val.strip())
            except ValueError:
                raise ValueError(
                    f"tenant spec {name!r}: {key}={val!r} is not a "
                    f"{keys[key].__name__}")
            if extra_keys and key in extra_keys:
                extra[key] = parsed
            else:
                kwargs[key] = parsed
        if "slo" in kwargs:
            kwargs["ttft_slo_s"] = kwargs.pop("slo")
        specs.append(TenantSpec(name, **kwargs))
        extras[name] = extra
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant names in {arg!r}")
    if extra_keys:
        return tuple(specs), extras
    return tuple(specs)


def format_tenants(specs) -> str:
    """Inverse of parse_tenants_arg (round-trips for logs/--dry-run)."""
    parts = []
    for s in specs:
        fields = [f"priority={s.priority}", f"weight={s.weight:g}"]
        if s.ttft_slo_s is not None:
            fields.append(f"slo={s.ttft_slo_s:g}")
        if s.max_queue is not None:
            fields.append(f"max_queue={s.max_queue}")
        parts.append(f"{s.name}:" + ",".join(fields))
    return ";".join(parts)
