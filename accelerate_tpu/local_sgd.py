"""Local SGD: skip cross-host gradient sync, periodically average params.

TPU-native analogue of ref src/accelerate/local_sgd.py:19-102. The reference
wraps a DDP model in `no_sync()` and every ``local_sgd_steps`` all-reduces the
module parameters (`_sync_and_avg_model_params` ref local_sgd.py:76).

On TPU the translation is sharper: *within* a slice, gradients ride ICI and
are averaged implicitly by GSPMD — skipping that sync buys nothing and is not
expressible under one jit program. Local SGD's entire value is avoiding the
*slow* interconnect, which for TPU is DCN between hosts/slices. So here each
host (or slice) trains on its own local mesh with no cross-host collectives,
and `LocalSGD.step(state)` averages the parameter pytree across host
processes every ``local_sgd_steps`` calls (and once more on context exit,
matching ref local_sgd.py:57-60).

Single-process worlds pass through untouched, mirroring the reference's
``enabled=False`` / NO fallback (ref local_sgd.py:30-36).
"""

from __future__ import annotations

from typing import Any

import jax

from .state import PartialState


def _cross_host_mean(pytree: Any) -> Any:
    """Average a (host-local, replicated-on-mesh) pytree across processes.

    Uses `process_allgather` (host-object collective over the JAX coordinator,
    replacing the reference's torch.distributed all_reduce of module params)
    then a local mean. `process_allgather` returns host numpy arrays, so the
    mean is explicitly `device_put` back onto each leaf's original sharding —
    otherwise the next jitted step would see unsharded host arrays (donation
    failure / implicit transfer to device 0).
    """
    from jax.experimental import multihost_utils

    def _avg(x):
        if not hasattr(x, "dtype"):
            return x
        stacked = multihost_utils.process_allgather(x)
        mean = stacked.mean(axis=0).astype(x.dtype)
        sharding = getattr(x, "sharding", None)
        return jax.device_put(mean, sharding) if sharding is not None else mean

    return jax.tree_util.tree_map(_avg, pytree)


class LocalSGD:
    """Context manager running Local SGD across host processes.

    Usage (mirrors ref local_sgd.py docstring example)::

        with LocalSGD(accelerator, local_sgd_steps=8) as local_sgd:
            for batch in loader:
                state, metrics = train_step(state, batch)
                state = local_sgd.step(state)

    `step` accepts and returns the params pytree or a TrainState; unlike the
    torch version (stateful module mutated in place) the averaged state must
    be threaded back by the caller — the functional-JAX contract.
    """

    def __init__(
        self,
        accelerator=None,
        model: Any = None,  # accepted for ref API parity; unused (params are explicit)
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ) -> None:
        if local_sgd_steps <= 0:
            raise ValueError(f"local_sgd_steps must be positive, got {local_sgd_steps}")
        state = PartialState()
        self.num_processes = state.num_processes
        self.enabled = enabled and self.num_processes > 1
        self.local_sgd_steps = local_sgd_steps
        self.local_step = 0
        self._dirty = False

    def __enter__(self) -> "LocalSGD":
        self.local_step = 0
        self._dirty = False
        return self

    def __exit__(self, exc_type, exc_value, traceback) -> None:
        # The reference's __exit__ averages the (in-place-mutable) torch module
        # one last time (ref local_sgd.py:57-60). Params here are immutable
        # pytrees the caller owns, so the final average must be threaded
        # through `flush(state)` — warn if the caller forgot.
        if exc_type is None and self.enabled and self._dirty:
            import warnings

            warnings.warn(
                "LocalSGD context exited with unsynced local steps; call "
                "`state = local_sgd.flush(state)` before leaving the block so "
                "all hosts end with identical parameters.",
                stacklevel=2,
            )

    def step(self, state: Any) -> Any:
        """Count one optimizer step; average across hosts at the boundary."""
        self.local_step += 1
        if not self.enabled:
            return state
        self._dirty = True
        if self.local_step % self.local_sgd_steps == 0:
            self._dirty = False
            return self._sync(state)
        return state

    def flush(self, state: Any) -> Any:
        """Explicit final average (functional alternative to __exit__)."""
        if self.enabled and self._dirty:
            self._dirty = False
            return self._sync(state)
        return state

    def _sync(self, state: Any) -> Any:
        if hasattr(state, "params") and hasattr(state, "replace"):
            return state.replace(params=_cross_host_mean(state.params))
        return _cross_host_mean(state)
