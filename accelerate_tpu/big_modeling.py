"""Big-model init, dispatch, and offloaded inference.

TPU-native analogue of ref src/accelerate/big_modeling.py (627 LoC) +
hooks.py (709 LoC). The reference's machinery is torch-shaped: meta-device
init, per-module ``device_map``, ``AlignDevicesHook`` moving weights at
forward time (ref hooks.py:315-383). Here:

- meta init  = ``jax.eval_shape`` (``init_empty_weights``) — shapes/dtypes
  with zero bytes allocated, no monkey-patching needed
  (ref big_modeling.py:56-166).
- the *preferred* multi-device path is GSPMD: ``dispatch_model`` with
  ``device_map="sharded"`` delegates to sharding/planner.py (TP+FSDP specs),
  and one jit'd forward runs across all chips — no per-module hooks, XLA
  inserts the collectives. This is the TPU answer to naive model parallel.
- the *offload* path keeps row groups of scan-stacked layer modules on
  device / host RAM / disk (``RowGroups``), and ``streamed_forward`` plays
  the AlignDevicesHook role: device_put each layer's slice right before its
  compiled step, double-buffered so the host→device copy of layer i+1
  overlaps compute of layer i (ref hooks.py pre_forward/post_forward,
  without graph breaks).
"""

from __future__ import annotations

import functools
import os
from collections import OrderedDict
from typing import Any, Callable, Mapping

import jax
import numpy as np

from .logging import get_logger
from .utils.modeling import (
    check_device_map,
    find_stacked_modules,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    load_state_dict,
    _LAYER_ROW,
)
from .utils.offload import load_offloaded_weight, offload_weight, save_offload_index
from .utils.other import flatten_dict, unflatten_dict

logger = get_logger(__name__)

__all__ = [
    "init_empty_weights",
    "init_on_device",
    "infer_auto_device_map",
    "get_balanced_memory",
    "get_max_memory",
    "dispatch_model",
    "load_checkpoint_and_dispatch",
    "cpu_offload",
    "disk_offload",
    "RowGroups",
    "streamed_forward",
]


def init_empty_weights(init_fn: Callable, *args, **kwargs) -> Any:
    """Abstract params: shapes/dtypes only, nothing allocated
    (ref big_modeling.py:56-102 ``init_empty_weights``; here it is just
    ``jax.eval_shape`` — JAX's tracing *is* the meta device). All arguments
    are closed over (static), so configs/dtypes pass through untouched."""
    return jax.eval_shape(lambda: init_fn(*args, **kwargs))


def init_on_device(device) -> Any:
    """Context manager placing fresh arrays on `device`
    (ref big_modeling.py:105-166)."""
    return jax.default_device(device)


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


class RowGroups:
    """A scan-stacked leaf split into contiguous row groups living on
    different storage tiers: jax.Array (device), np.ndarray (host), or
    np.memmap (disk). ``row(i)`` fetches one layer's slice."""

    def __init__(self, groups: list[tuple[int, int, Any]], shape, dtype):
        self.groups = sorted(groups, key=lambda g: g[0])
        self.shape = tuple(shape)
        self.dtype = dtype

    def row(self, i: int):
        for start, end, arr in self.groups:
            if start <= i < end:
                return arr[i - start]
        raise IndexError(i)

    def __repr__(self) -> str:
        tiers = [
            f"[{s}:{e})->{'dev' if isinstance(a, jax.Array) else 'host'}"
            for s, e, a in self.groups
        ]
        return f"RowGroups({', '.join(tiers)})"


def _resolve_target(target):
    """device_map value -> ('device', jax.Device) | ('cpu'|'disk', None)."""
    if target in ("cpu", "disk"):
        return (target, None)
    if isinstance(target, int):
        return ("device", jax.local_devices()[target])
    return ("device", target)  # already a jax.Device


def _placement_plan(params: Any, device_map: Mapping[str, Any]) -> dict[str, Any]:
    """flat key -> target, or (for stacked leaves with per-row map entries)
    list of (start_row, end_row, target)."""
    check_device_map(params, device_map)
    flat = flatten_dict(params)
    stacked = find_stacked_modules(params)
    # collect per-module row assignments: {'layers': {0: dev, 1: 'cpu', ...}}
    row_maps: dict[str, dict[int, Any]] = {}
    plain: dict[str, Any] = {}
    for key, target in device_map.items():
        m = _LAYER_ROW.match(key)
        if m and m.group(1) in stacked:
            row_maps.setdefault(m.group(1), {})[int(m.group(2))] = target
        elif m and isinstance(params, dict) and m.group(1) in params:
            raise ValueError(
                f"device_map key {key!r} addresses module {m.group(1)!r} per-row, "
                "but it is not a stacked scan-layer module"
            )
        else:
            plain[key] = target

    plan: dict[str, Any] = {}
    for key in flat:
        mod = key.split(".", 1)[0]
        if mod in row_maps:
            rows = row_maps[mod]
            n = stacked[mod]
            groups: list[tuple[int, int, Any]] = []
            for i in range(n):
                t = rows.get(i, "cpu")
                if groups and groups[-1][2] == t:
                    groups[-1] = (groups[-1][0], i + 1, t)
                else:
                    groups.append((i, i + 1, t))
            plan[key] = groups if len(groups) > 1 else groups[0][2]
        else:
            hits = [mk for mk in plain if mk == "" or key == mk or key.startswith(mk + ".")]
            plan[key] = plain[max(hits, key=len)]
    return plan


def _place_one(key: str, arr, target, offload_folder, offload_index):
    kind, dev = _resolve_target(target)
    if kind == "device":
        return jax.device_put(arr, dev)
    if kind == "cpu":
        return np.asarray(arr)
    if offload_folder is None:
        raise ValueError(f"{key!r} mapped to disk but no offload_folder given")
    offload_weight(arr, key, offload_folder, offload_index)
    return load_offloaded_weight(
        os.path.join(offload_folder, f"{key}.dat"), offload_index[key]
    )


_PLACE_BATCH_BYTES = 1 << 30  # ~1 GB of host staging per transfer batch


def _place_flat(
    flat: Mapping[str, Any], plan: Mapping[str, Any], offload_folder: str | None
) -> tuple[dict[str, Any], dict]:
    """Place every leaf per the plan.

    Device-bound arrays are transferred in ~1 GB batched `jax.device_put`
    calls instead of one call per array: on a tunneled/remote device each
    call pays a round trip, which serialized the r4 gptj-6b load to ~28%
    of link bandwidth (VERDICT r4 weak #4). Batching amortizes the round
    trips, and because `device_put` is asynchronous, the next batch's disk
    reads (memmapped safetensors slices materialize here) overlap the
    previous batch's in-flight transfers. Host RAM staging stays bounded
    by the batch size.
    """
    offload_index: dict = {}
    out: dict[str, Any] = {}
    pending: list[tuple] = []  # (setter, np.ndarray, device)
    pending_bytes = 0

    def flush() -> None:
        nonlocal pending, pending_bytes
        if not pending:
            return
        placed = jax.device_put([p[1] for p in pending],
                                [p[2] for p in pending])
        for (setter, _, _), value in zip(pending, placed):
            setter(value)
        pending, pending_bytes = [], 0

    def place(key: str, arr, target, setter) -> None:
        nonlocal pending_bytes
        kind, dev = _resolve_target(target)
        if kind == "device":
            if (
                isinstance(arr, jax.Array)
                and getattr(arr, "_committed", False)
                and all(
                    d.platform != "cpu" for d in arr.sharding.device_set
                )
            ):
                # already resident on an accelerator (e.g. re-dispatching a
                # loaded model): np.asarray here would pull it device->host
                # and re-upload through the staging batches. device_put moves
                # it device->device (or leaves it in place) instead.
                setter(_place_one(key, arr, target, offload_folder,
                                  offload_index))
                return
            arr = np.asarray(arr)
            pending.append((setter, arr, dev))
            pending_bytes += arr.nbytes
            if pending_bytes >= _PLACE_BATCH_BYTES:
                flush()
        else:
            setter(_place_one(key, arr, target, offload_folder, offload_index))

    # deferred RowGroups: group slots fill as batches flush, so the
    # objects are built only after the final flush
    row_accum: dict[str, tuple[list, tuple, Any]] = {}
    for key, arr in flat.items():
        target = plan[key]
        if isinstance(target, list):  # row groups of a stacked leaf
            groups: list = [None] * len(target)
            row_accum[key] = (groups, arr.shape, arr.dtype)
            for i, (start, end, t) in enumerate(target):
                def set_group(v, groups=groups, i=i, start=start, end=end):
                    groups[i] = (start, end, v)
                place(f"{key}.rows{start}-{end}", np.asarray(arr[start:end]),
                      t, set_group)
        else:
            def set_out(v, key=key):
                out[key] = v
            place(key, arr, target, set_out)
    flush()
    for key, (groups, shape, dtype) in row_accum.items():
        out[key] = RowGroups(groups, shape, dtype)
    return out, offload_index


def dispatch_model(
    params: Any,
    device_map: Mapping[str, Any] | str | None = "sharded",
    offload_folder: str | None = None,
    mesh_axis: str = "model",
) -> Any:
    """Lay a params pytree out across devices (ref big_modeling.py:305-495).

    - ``device_map='sharded'`` (default, the TPU-idiomatic path): build a 1-D
      mesh over all local devices and apply the transformer sharding rules —
      the whole model runs in one jit, GSPMD moving data. Replaces per-module
      hooks entirely.
    - explicit ``{module: device|'cpu'|'disk'}`` map (including per-row
      ``layers.{i}`` entries from ``infer_auto_device_map``): leaves are
      placed per tier; host/disk row groups come back as ``RowGroups`` for
      ``streamed_forward``.
    """
    if device_map == "sharded" or device_map is None:
        from jax.sharding import Mesh

        from .sharding.planner import plan_sharding, shard_pytree
        from .sharding.rules import transformer_rules

        devices = np.array(jax.local_devices())
        mesh = Mesh(devices, (mesh_axis,))
        plan = plan_sharding(params, mesh, rules=transformer_rules())
        return shard_pytree(params, plan)
    if device_map == "auto":
        device_map = infer_auto_device_map(params)
    plan = _placement_plan(params, device_map)
    flat = flatten_dict(params)
    placed, offload_index = _place_flat(flat, plan, offload_folder)
    if offload_index and offload_folder:
        save_offload_index(offload_index, offload_folder)
    return unflatten_dict(placed)


def cpu_offload(params: Any, keep_modules: tuple = ()) -> Any:
    """All params to host RAM except `keep_modules`
    (ref big_modeling.py:169-212)."""
    device_map = OrderedDict(
        (name, 0 if name in keep_modules else "cpu") for name in params
    )
    return dispatch_model(params, device_map)


def disk_offload(params: Any, offload_folder: str, keep_modules: tuple = ()) -> Any:
    """All params to disk memmaps except `keep_modules`
    (ref big_modeling.py:259-302)."""
    device_map = OrderedDict(
        (name, 0 if name in keep_modules else "disk") for name in params
    )
    return dispatch_model(params, device_map, offload_folder=offload_folder)


def load_checkpoint_and_dispatch(
    params_abstract: Any,
    checkpoint: str,
    device_map: Mapping[str, Any] | str | None = "auto",
    max_memory: dict | None = None,
    no_split_modules: tuple = (),
    offload_folder: str | None = None,
    dtype=None,
) -> Any:
    """Stream a checkpoint straight onto its planned placement
    (ref big_modeling.py:498-627). `params_abstract` comes from
    ``init_empty_weights`` — nothing is materialized host-side beyond one
    tensor at a time for safetensors checkpoints."""
    if device_map in ("auto", "balanced"):
        device_map = infer_auto_device_map(
            params_abstract, max_memory=max_memory,
            no_split_modules=no_split_modules, dtype=dtype,
        )
    if device_map == "sharded":
        if checkpoint.endswith((".safetensors", ".bin")):
            loaded = unflatten_dict(load_state_dict(checkpoint))
        else:
            from .checkpointing import load_model

            loaded = load_model(checkpoint)
        return dispatch_model(loaded, "sharded")
    loaded, _ = load_checkpoint_in_model(
        params_abstract, checkpoint, device_map=device_map,
        offload_folder=offload_folder, dtype=dtype,
    )
    return loaded


# ---------------------------------------------------------------------------
# streamed forward (the AlignDevicesHook replacement)
# ---------------------------------------------------------------------------


def _module_rowgroups(params_mod: dict) -> bool:
    return any(
        isinstance(l, RowGroups)
        for l in jax.tree_util.tree_leaves(params_mod, is_leaf=lambda x: isinstance(x, RowGroups))
    )


def _fetch_leaf(leaf, device, dtype):
    if isinstance(leaf, jax.Array):
        # cast device-resident leaves too: mixed tiers must execute at one
        # dtype or the jit'd layer body recompiles per tier boundary
        return leaf.astype(dtype) if dtype is not None else leaf
    arr = np.asarray(leaf)
    if dtype is not None:
        arr = arr.astype(dtype)
    return jax.device_put(arr, device)


def fetch_resident(params: Any, stacked_module: str, device, dtype) -> dict:
    """Bring every non-stacked module (embeddings, final norm, head) fully
    onto the device once — they are touched every step and are small next to
    the stacked layers."""
    return {
        k: jax.tree_util.tree_map(lambda l: _fetch_leaf(l, device, dtype), v)
        for k, v in params.items()
        if k != stacked_module
    }


def make_layer_slicer(stacked: Any, device, dtype):
    """(n_layers, slice_fn) where slice_fn(i) fetches layer i's params from
    wherever they live (device array / host RAM / disk memmap —
    ``RowGroups.row``) as an async device_put, so fetching layer i+1 overlaps
    layer i's compute."""
    flat_stacked = flatten_dict(stacked)
    n_layers = min(leaf.shape[0] for leaf in flat_stacked.values())

    def _layer_slice(i: int):
        def get(leaf):
            row = leaf.row(i) if isinstance(leaf, RowGroups) else leaf[i]
            if isinstance(row, jax.Array):
                return row.astype(dtype) if dtype is not None else row
            row = np.asarray(row)
            if dtype is not None:
                row = row.astype(dtype)
            return jax.device_put(row, device)

        return jax.tree_util.tree_map(
            get, stacked, is_leaf=lambda x: isinstance(x, RowGroups)
        )

    return n_layers, _layer_slice


def stream_layers(layer_slice, n_layers: int, step_fn, x):
    """Drive the double-buffered layer loop: fetch layer i+1 (async H2D)
    while layer i computes. `step_fn(layer, i, x) -> x`. The single home of
    the prefetch-overlap invariant for streamed_forward/streamed_generate
    and T5's streamed encoder.

    Each iteration BLOCKS on layer i's output before issuing layer i+2's
    fetch: async dispatch would otherwise let the Python loop queue every
    layer's host→device copy at once, and on a slow link the in-flight
    transfer buffers sum to the whole model in host RAM (observed as an
    OOM-kill streaming a 41 GB checkpoint). The barrier is a one-element
    device→host READ, not block_until_ready — tunneled/experimental
    backends have been observed returning from block_until_ready without
    waiting, which re-opens the pileup. The overlap of copy(i+1) with
    compute(i) — issued before the block — is preserved."""
    nxt = layer_slice(0)
    for i in range(n_layers):
        cur = nxt
        if i + 1 < n_layers:
            nxt = layer_slice(i + 1)
        x = step_fn(cur, i, x)
        probe = jax.tree_util.tree_leaves(x)[0]
        np.asarray(probe.ravel()[0])  # true sync: D2H of one element
    return x


def streamed_generate(
    params: Any,
    input_ids,
    *,
    embed_fn: Callable[[Any, Any, Any], Any],
    layer_step_fn: Callable[[Any, Any, Any, tuple], tuple],
    project_fn: Callable[[Any, Any], Any],
    init_layer_cache: Callable[[int, int], tuple],
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    key=None,
    stacked_module: str = "layers",
    device=None,
    dtype=None,
):
    """KV-cache greedy/temperature decode with (partly) offloaded stacked
    layers — the reference benchmark's cpu/disk-offload rows
    (ref benchmarks/README.md:27-36 "with cpu offload", ref
    big_modeling.py:305-495 dispatch + hooks path).

    Per decode step, each layer's params stream host→device double-buffered
    around a single jit'd ``layer_step_fn(layer, x, positions, (k, v,
    cache_len)) -> (x, new_cache)``; per-layer KV caches stay device-resident
    between steps (they are tiny next to the weights). ``embed_fn(resident,
    ids, positions)`` and ``project_fn(resident, x)`` run on the resident
    (non-stacked) modules.
    """
    import jax.numpy as jnp

    device = device or jax.local_devices()[0]
    resident = fetch_resident(params, stacked_module, device, dtype)
    n_layers, layer_slice = make_layer_slicer(
        params[stacked_module], device, dtype)

    b, prompt_len = input_ids.shape
    total = prompt_len + max_new_tokens
    caches = [init_layer_cache(b, total) for _ in range(n_layers)]
    cache_len = jnp.zeros((), jnp.int32)
    if key is None:
        key = jax.random.key(0)

    def run_stack(ids, positions, cache_len):
        new_len = [None]

        def step(layer, i, x):
            x, (nk, nv, nl) = layer_step_fn(
                layer, x, positions, (caches[i][0], caches[i][1], cache_len))
            caches[i] = (nk, nv)
            new_len[0] = nl
            return x

        x = stream_layers(layer_slice, n_layers, step,
                          embed_fn(resident, ids, positions))
        return project_fn(resident, x), new_len[0]

    from .models.decode import sample_token

    def select(logits, k):
        return sample_token(logits, k, temperature)

    positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
    ids = jnp.asarray(input_ids)
    logits, cache_len = run_stack(ids, positions, cache_len)
    key, sub = jax.random.split(key)
    tokens = [select(logits, sub)]
    for t in range(prompt_len, total - 1):
        pos = jnp.broadcast_to(jnp.int32(t), (b, 1))
        logits, cache_len = run_stack(tokens[-1][:, None], pos, cache_len)
        key, sub = jax.random.split(key)
        tokens.append(select(logits, sub))
    new = jnp.stack(tokens, axis=1)
    return jnp.concatenate([ids, new], axis=1)


def streamed_forward(
    params: Any,
    inputs: Any,
    embed_fn: Callable[[Any, Any], Any],
    layer_fn: Callable[[Any, Any, int], Any],
    final_fn: Callable[[Any, Any], Any],
    stacked_module: str = "layers",
    device=None,
    dtype=None,
) -> Any:
    """Run a scan-family model whose stacked layers are (partly) offloaded
    (ref hooks.py:212-517 AlignDevicesHook, functional form).

    For each layer i: slice its params from wherever they live (device array /
    host RAM / disk memmap — ``RowGroups.row``), ``device_put`` (async — the
    copy of layer i+1 overlaps layer i's compute), run the jit'd `layer_fn`.
    Non-stacked modules are fetched to the device once up front.
    """
    device = device or jax.local_devices()[0]
    resident = fetch_resident(params, stacked_module, device, dtype)
    n_layers, _layer_slice = make_layer_slicer(
        params[stacked_module], device, dtype)

    x = stream_layers(_layer_slice, n_layers,
                      lambda layer, i, x: layer_fn(layer, x, i),
                      embed_fn(resident, inputs))
    return final_fn(resident, x)


# ---------------------------------------------------------------------------
# quantized load (the bnb replacement, ref utils/bnb.py:44-467)
# ---------------------------------------------------------------------------


def load_and_quantize_params(
    params_abstract: Any,
    checkpoint: str,
    quantization_config=None,
    dtype=None,
    device_put: bool = True,
) -> Any:
    """Load a checkpoint and block-quantize weight matrices to int8/int4
    (ref `load_and_quantize_model` utils/bnb.py:44; kernels are ours —
    ops/quant.py — not bitsandbytes).

    The checkpoint is streamed host-side and quantized with numpy math —
    HBM only ever sees the compressed tensors (`device_put=True`), which is
    the point: the quantized model fits where the fp16 one would not. There
    is deliberately no device_map/offload here — after 4/8-bit compression a
    single host's HBM+RAM covers the reference's offload use cases; for
    larger-than-host models use sharded dispatch instead."""
    from .ops.quant import QuantizedTensor, quantize_params

    loaded, _ = load_checkpoint_in_model(
        params_abstract, checkpoint, device_map=None, dtype=dtype,
    )
    quantized = quantize_params(loaded, quantization_config)
    if not device_put:
        return quantized
    return jax.tree_util.tree_map(
        jax.device_put, quantized,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
