"""Sharded data loading.

TPU-native analogue of ref src/accelerate/data_loader.py (1149 LoC). The
reference wraps a torch DataLoader per *process* (one process per GPU) and
moves batches with `send_to_device`; torch-xla needed a background
`MpDeviceLoader` (ref data_loader.py:518-559). Here one process drives every
local chip, so the pipeline is:

    host iterable (1/num_hosts of each global batch)
        -> numpy pytree
        -> jax.make_array_from_process_local_data
        -> one *global* jax.Array per leaf, sharded over the mesh batch axes

Sharding across hosts keeps the reference's `BatchSamplerShard` /
`IterableDatasetShard` semantics (ref data_loader.py:100-390): `split_batches`,
`even_batches` wraparound duplication, seedable deterministic shuffling,
mid-epoch resume via `skip_first_batches` (ref :1082). The uneven-tail
`remainder` feeds `gather_for_metrics` (ref accelerator.py:2331).

Async input pipeline, two stages (the reference's one-batch-ahead lookahead,
ref data_loader.py:445-476, plus the torch-xla `MpDeviceLoader` double
buffer):

1. a background thread runs the HOST work (collate -> numpy -> pad) into a
   bounded queue (`_PrefetchIterator`), and
2. the consumer side keeps up to `device_prefetch_depth` batches' host->device
   transfers in flight (`DevicePrefetchIterator`) — `jax.device_put` /
   `make_array_from_process_local_data` are asynchronous, so batch i+1's
   transfer overlaps step i's on-device compute and the steady-state step
   never stalls on input.
"""

from __future__ import annotations

import collections
import functools
import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np

from .state import AcceleratorState, GradientState, PartialState
from .telemetry.trace import span
from .utils.constants import BATCH_AXES
from .utils.dataclasses import DataLoaderConfiguration, RNGType
from .utils.operations import (
    broadcast_object_list,
    concatenate,
    get_data_structure,
    send_to_device,
)
from .utils.random import synchronize_rng_states
from .logging import get_logger

logger = get_logger(__name__)

_SENTINEL = object()


# ---------------------------------------------------------------------------
# host-side leaf conversion
# ---------------------------------------------------------------------------


def _to_numpy(x: Any) -> Any:
    if isinstance(x, np.ndarray):
        return x
    if isinstance(x, np.generic):  # numpy scalar -> 0-d array
        return np.asarray(x)
    if isinstance(x, (int, float, bool)):
        return x
    # torch tensors (CPU interop) expose .numpy(); jax arrays pass through
    if hasattr(x, "detach"):
        return x.detach().cpu().numpy()
    if isinstance(x, jax.Array):
        return np.asarray(x)
    if isinstance(x, (list, tuple)) and x and isinstance(x[0], (int, float)):
        return np.asarray(x)
    return x


def batch_to_numpy(batch: Any, keep_device_arrays: bool = False) -> Any:
    """Convert a host batch (torch tensors / lists / numpy) to numpy leaves.

    `keep_device_arrays=True` passes `jax.Array` leaves through untouched:
    the device-placement path (`make_global_batch`) reshards them
    device->device, so converting here would force a synchronous
    device->host pull that immediately gets pushed back (the self-lint
    ATP003 hazard, in host-code form)."""
    if keep_device_arrays:
        return jax.tree_util.tree_map(
            lambda x: x if isinstance(x, jax.Array) else _to_numpy(x), batch
        )
    return jax.tree_util.tree_map(_to_numpy, batch)


def _batch_size(batch: Any) -> int | None:
    """find_batch_size plus the row-container case: a row container (a list
    of strings/scalars/ragged sequences) anywhere in the tree contributes its
    len() as the row count — find_batch_size alone would return the first
    ragged row's *token* count for e.g. {'ids': [arr, ...], 'x': array}.

    Evidence priority, independent of dict key order: (1) array leading
    dims — arrays are the collated fields and their leading dim IS the batch
    size (a short metadata string list must not override it); (2) row
    containers (ragged/scalar/string lists); (3) ambiguous equal-length 1-D
    lists, via their first array's leading dim (the field interpretation,
    matching find_batch_size)."""
    containers: list = []
    deferred: list = []

    def walk(node) -> int | None:
        if _is_row_container(node):
            if len(node):
                containers.append(node)  # empty ones carry no evidence
            return None
        if (
            isinstance(node, (list, tuple))
            and not hasattr(node, "_fields")
            and node
            and getattr(node[0], "ndim", None) == 1
        ):
            deferred.append(node)  # ambiguous: equal-length 1-D rows/fields
            return None
        # numpy / torch / jax arrays all expose .ndim and .shape
        if getattr(node, "ndim", 0):
            dims.append(int(node.shape[0]))
            return None
        if isinstance(node, dict):
            children = (v for _, v in sorted(node.items(), key=lambda kv: str(kv[0])))
        elif isinstance(node, (list, tuple)):
            children = iter(node)
        else:
            return None
        for child in children:
            walk(child)
        return None

    dims: list[int] = []
    walk(batch)
    if dims:
        # the MAJORITY leading dim is the batch size: first-found would let
        # an aux array whose key merely sorts first (e.g. 'a_weights' [3])
        # hijack the batch size and misclassify the real data as aux
        # (advisor r2 finding). Ties break toward the first-seen dim, which
        # preserves the old behavior for uniform batches.
        from collections import Counter

        counts = Counter(dims)
        best = max(counts.values())
        for d in dims:
            if counts[d] == best:
                return d
    for node in containers:
        return len(node)
    for node in deferred:
        return int(np.shape(node[0])[0])
    return None


def _wrap_pad_rows(x: Any, target: int) -> Any:
    """Wraparound-extend the rows of a list/tuple (row container) or the
    leading dim of an array up to `target`; anything else passes through."""
    if isinstance(x, (list, tuple)):
        if len(x) == 0 or len(x) >= target:
            return x
        reps = math.ceil(target / len(x))
        return type(x)((list(x) * reps)[:target])
    if not isinstance(x, np.ndarray) or x.ndim == 0 or x.shape[0] >= target:
        return x
    reps = math.ceil(target / x.shape[0])
    return np.concatenate([x] * reps, axis=0)[:target]


def _is_row_container(x: Any, expected_rows: int | None = None) -> bool:
    """True for a list/tuple whose elements are individual *rows* (strings /
    scalars) rather than pytree structure. A tuple batch like
    (inputs, labels) holds arrays and is structure, so tree_map recurses into
    it and each field is sliced/padded row-wise; a list of strings is a leaf
    sliced whole."""
    if not isinstance(x, (list, tuple)) or hasattr(x, "_fields"):
        # namedtuples are pytree structure (fixed fields), never row batches
        return False
    if len(x) == 0:
        return True
    head = x[0]
    if isinstance(x, tuple):
        # numeric tuples like (224, 224) are almost always metadata, and a
        # tuple of arrays like (inputs, labels) is a field pair, not a
        # 2-row batch; only string/bytes tuples count as rows, so slicing
        # and padding agree on what is a row container
        return isinstance(head, (str, bytes))
    # lists: scalar-like rows, ragged token sequences (lists of lists, the
    # HF tokenizer output shape), or 0-d arrays are rows; a list of >=2-D
    # arrays or dicts is field structure
    if isinstance(
        head,
        (str, bytes, int, float, bool, complex, type(None), np.generic, list),
    ):
        return True
    # numpy / torch / jax arrays all expose .ndim — classify generically so
    # torch-tensor rows behave exactly like numpy rows
    head_ndim = getattr(head, "ndim", None)
    if head_ndim == 0:
        return True
    if head_ndim == 1:
        # a list of 1-D arrays is ambiguous: ragged token rows, or the
        # [features, labels] field list torch's default_collate emits for
        # scalar-sample datasets. Varying lengths mean ragged rows; for
        # equal lengths the batch's known row count disambiguates (a list
        # with one entry per row is rows, a short field list is structure).
        # Without that context, equal lengths default to field structure —
        # pad genuinely ragged-but-equal batches into a 2-D array instead.
        lengths = {len(e) for e in x if getattr(e, "ndim", None) == 1}
        if len(lengths) > 1:
            return True
        if expected_rows is None or len(x) != expected_rows:
            return False
        # square case (k fields of k samples vs k rows of k tokens) is
        # undecidable — default to the default_collate field interpretation
        (inner,) = lengths or {0}
        return inner != expected_rows
    return False


# ---------------------------------------------------------------------------
# samplers / shards (ref data_loader.py:67-390)
# ---------------------------------------------------------------------------


class SeedableRandomSampler:
    """Deterministic epoch-seeded permutation sampler
    (ref data_loader.py:67 `SeedableRandomSampler`). Every host computes the
    same permutation from (seed, epoch) — no rank-0 broadcast needed."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()


class BatchSamplerShard:
    """Shard a stream of batch indices across `num_processes` hosts
    (ref data_loader.py:100-255).

    `split_batches=False`: each host takes batches round-robin (host i gets
    batch i, i+N, ...). `split_batches=True`: every batch is split into N
    equal slices. `even_batches=True` wraps around to duplicate initial
    samples so every host yields the same number of equally-sized batches.
    """

    def __init__(
        self,
        batch_sampler: Iterable,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def __len__(self) -> int:
        length = len(self.batch_sampler)  # type: ignore[arg-type]
        if self.split_batches:
            return length
        if length % self.num_processes == 0:
            return length // self.num_processes
        return length // self.num_processes + (0 if self.drop_last else 1)

    def __iter__(self) -> Iterator[list]:
        if self.split_batches:
            yield from self._iter_split()
        else:
            yield from self._iter_stride()

    def _iter_split(self) -> Iterator[list]:
        for batch in self.batch_sampler:
            # validated lazily: an __init__-time peek would consume the first
            # batch of one-shot iterators/generators
            if len(batch) % self.num_processes != 0:
                raise ValueError(
                    f"split_batches=True requires batch size ({len(batch)}) "
                    f"divisible by num_processes ({self.num_processes})"
                )
            chunk = len(batch) // self.num_processes
            start = self.process_index * chunk
            yield batch[start : start + chunk]

    def _iter_stride(self) -> Iterator[list]:
        initial: list[list] = []
        cursor = 0
        mine = None
        batch_size = None
        for batch in self.batch_sampler:
            if len(initial) < self.num_processes:
                initial.append(batch)
            if batch_size is None:
                batch_size = len(batch)
            if cursor % self.num_processes == self.process_index:
                mine = batch
            cursor += 1
            if cursor % self.num_processes == 0:
                yield mine
                mine = None
        if cursor % self.num_processes == 0:
            return
        # uneven tail (ref data_loader.py:208-255)
        if self.drop_last:
            return
        if not self.even_batches:
            if mine is not None:
                yield mine
            return
        # wraparound: complete the final round with recycled initial batches,
        # padding short batches to full size by duplicating from the start.
        pool = list(itertools.chain.from_iterable(initial))
        tail_count = cursor % self.num_processes
        if self.process_index < tail_count:
            batch = mine if mine is not None else []
        else:
            batch = []
        if batch_size is not None and len(batch) < batch_size and pool:
            need = batch_size - len(batch)
            offset = (self.process_index * batch_size) % max(len(pool), 1)
            filler = [pool[(offset + j) % len(pool)] for j in range(need)]
            batch = list(batch) + filler
        yield batch


class ShardedBatchIterable:
    """Shard a sized stream of pre-assembled batches across hosts — the
    plain-iterable analogue of `BatchSamplerShard` (ref data_loader.py:100).

    Two modes (matching the reference's split_batches switch):
    - stride (default): batch i goes to host i % P. `even_batches=True`
      recycles initial batches and pads a short FINAL batch up to the size of
      the first batch, so every host yields the same number of equally-shaped
      batches and SPMD steps stay in lockstep; a mid-stream batch whose size
      differs raises (its padding would corrupt `remainder`). With
      `even_batches=False` nothing pads and variable sizes are legal.
    - split (`split_batches=True`): every host takes its contiguous slice of
      EVERY batch, so the global batch size equals the source batch size.

    Unlike the reference's sampler-level wraparound, the duplicated/padded
    rows of the final round ARE tracked: after full iteration, `remainder`
    holds the number of REAL rows in the final global round (-1 if none were
    duplicated) so `gather_for_metrics` can drop the filler tail.
    """

    def __init__(self, batches, num_processes: int, process_index: int,
                 even_batches: bool = True, split_batches: bool = False):
        self.batches = batches
        self.num_processes = num_processes
        self.process_index = process_index
        self.even_batches = even_batches
        self.split_batches = split_batches
        self.batch_size = getattr(batches, "batch_size", None)
        self.remainder = -1
        self.tail_layout = None

    def __len__(self) -> int:
        n = len(self.batches)  # type: ignore[arg-type]
        if self.split_batches:
            return n
        q, r = divmod(n, self.num_processes)
        if r == 0:
            return q
        if self.even_batches:
            return q + 1
        return q + (1 if self.process_index < r else 0)

    def __iter__(self):
        if self.split_batches:
            yield from self._iter_split_mode()
        else:
            yield from self._iter_stride_mode()

    def _iter_split_mode(self):
        """Each host slices rows [rank*B/P, (rank+1)*B/P) of every batch."""
        P, rank = self.num_processes, self.process_index
        n = len(self.batches)  # type: ignore[arg-type]
        self.remainder = -1
        self.tail_layout = None
        full_size = None
        for cursor, batch in enumerate(self.batches):
            size = _batch_size(batch)
            if full_size is None:
                if size is None or size % P != 0:
                    raise ValueError(
                        f"split_batches=True needs batch size divisible by "
                        f"{P} processes, got {size}"
                    )
                full_size = size
            if size is None:
                raise ValueError(
                    f"batch {cursor} has no measurable batch size (no array "
                    "leaves or row container); split_batches needs sized "
                    "batches"
                )
            if size > full_size:
                # slicing would silently drop rows beyond full_size
                raise ValueError(
                    f"batch {cursor} has {size} rows but the first batch had "
                    f"{full_size}; batches may not grow with split_batches"
                )
            if size < full_size:  # short tail: pad below, record true rows
                if cursor != n - 1:
                    raise ValueError(
                        "only the final batch may be short with split_batches"
                    )
                if not self.even_batches:
                    # slicing a short batch into B/P-row pieces would give
                    # hosts different shapes in the same SPMD step
                    raise ValueError(
                        f"split_batches with even_batches=False cannot split "
                        f"a short final batch ({size} rows < {full_size}); "
                        "drop it or enable even_batches"
                    )
                self.remainder = size
            per = full_size // P
            true_rows = size

            # pad + slice in ONE pass so every leaf is classified exactly
            # once, against the batch's true (pre-pad) row count — padding
            # first and re-classifying at slice time can flip an equal-length
            # ragged tail from rows to field structure. A row container (a
            # list of strings / scalars / ragged sequences) wraparound-pads
            # and slices whole; arrays pad and slice their leading dim;
            # structure (dicts, field tuples) is recursed into by tree_map.
            def _prepare(x):
                if _is_row_container(x, true_rows):
                    if len(x) != true_rows:
                        # metadata container (e.g. a short label-name list):
                        # replicate untouched, never wrap/slice
                        return x
                    x = _wrap_pad_rows(x, full_size)
                    return x[rank * per : (rank + 1) * per]
                x = _to_numpy(x)
                if isinstance(x, np.ndarray) and x.ndim > 0:
                    if x.shape[0] != true_rows:
                        # aux array (e.g. per-class weights): replicate
                        return x
                    x = _wrap_pad_rows(x, full_size)
                    return x[rank * per : (rank + 1) * per]
                return x  # strings/scalars/0-d leaves replicate

            yield jax.tree_util.tree_map(
                _prepare, batch,
                is_leaf=lambda x: _is_row_container(x, true_rows),
            )

    def _iter_stride_mode(self):
        P, rank = self.num_processes, self.process_index
        n = len(self.batches)  # type: ignore[arg-type]
        self.remainder = -1
        self.tail_layout = None
        tail = n % P
        # which batch (if any) this host recycles to complete the final round
        recycle_idx = None
        if tail and self.even_batches and rank >= tail:
            recycle_idx = (rank - tail) % min(P, n)
        recycled = None
        full_size = None
        last_size = None
        for cursor, batch in enumerate(self.batches):
            size = _batch_size(batch)
            if full_size is None:
                full_size = size
            if cursor == n - 1:
                last_size = size
                if (
                    self.even_batches
                    and size is not None
                    and full_size is not None
                    and size > full_size
                ):
                    # _pad_to_full only pads upward: an oversized final batch
                    # would leave this rank's final round bigger than its
                    # peers', breaking SPMD lockstep
                    raise ValueError(
                        f"final batch has {size} rows but earlier batches had "
                        f"{full_size}; batches may not grow when "
                        "even_batches=True"
                    )
            elif (
                self.even_batches
                and size is not None
                and full_size is not None
                and size != full_size
            ):
                # the remainder bookkeeping below (and gather_for_metrics'
                # truncation built on it) assumes only the final batch can be
                # short — a padded mid-stream batch would leak filler rows
                # into gather_for_metrics as real samples. even_batches=False
                # never pads, so variable-size streams stay legal there.
                raise ValueError(
                    f"batch {cursor} has {size} rows but the first batch had "
                    f"{full_size}; only the final batch may be short "
                    "when even_batches=True"
                )
            if cursor == recycle_idx:
                recycled = batch
            if cursor % P == rank:
                if self.even_batches:
                    batch = self._pad_to_full(batch, full_size)
                yield batch
        if recycled is not None:
            yield self._pad_to_full(recycled, full_size)
        # real rows of the final global round (ranks in order: the batches
        # n-t..n-1 land on ranks 0..t-1, recycled duplicates after), so
        # `[:remainder]` truncation of a gathered final round keeps exactly
        # the real samples
        if self.even_batches and full_size is not None and last_size is not None:
            t = tail if tail else P
            if tail or last_size < full_size:
                if n >= P or tail:
                    self.remainder = (min(t, n) - 1) * full_size + last_size

    @staticmethod
    def _pad_to_full(batch, full_size):
        """Keep per-host shapes identical: a short batch is padded up to the
        size of a full batch."""
        if full_size is None:
            return batch
        size = _batch_size(batch)
        if size is not None and size < full_size:
            return pad_batch_to(batch, full_size, rows=size)
        return batch


class IterableDatasetShard:
    """Shard an *iterable* source of samples across hosts
    (ref data_loader.py:256-390): buffer `batch_size * num_processes`
    samples, then each host takes its slice."""

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        num_processes: int = 1,
        process_index: int = 0,
        drop_last: bool = False,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_processes = num_processes
        self.process_index = process_index
        self.drop_last = drop_last
        self.split_batches = split_batches

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self) -> Iterator:
        real_batch_size = (
            self.batch_size
            if self.split_batches
            else self.batch_size * self.num_processes
        )
        slice_width = real_batch_size // self.num_processes
        my_range = range(
            self.process_index * slice_width, (self.process_index + 1) * slice_width
        )
        buffer: list = []
        first_loop_items: list = []
        for element in self.dataset:
            buffer.append(element)
            if len(first_loop_items) < real_batch_size:
                first_loop_items.append(element)
            if len(buffer) == real_batch_size:
                for i in my_range:
                    yield buffer[i]
                buffer = []
        if not self.drop_last and buffer:
            while len(buffer) < real_batch_size:
                buffer += first_loop_items[: real_batch_size - len(buffer)]
            for i in my_range:
                yield buffer[i]


# ---------------------------------------------------------------------------
# global-array assembly
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _mesh_batch_layout(mesh, batch_axes: tuple):
    """(batch NamedSharding, replicated NamedSharding, dp) for a mesh — the
    per-batch sharding objects are identical every step, so they are resolved
    once per (mesh, axes) instead of rebuilt per leaf per batch (host-dispatch
    cost on the hot input path)."""
    axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    dp = 1
    for a in axes:
        dp *= mesh.shape[a]
    spec = jax.sharding.PartitionSpec(
        axes if len(axes) > 1 else axes[0] if axes else None
    )
    return (
        jax.sharding.NamedSharding(mesh, spec),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        dp,
    )


def make_global_batch(batch: Any, mesh=None, batch_axes=BATCH_AXES) -> Any:
    """Assemble per-host numpy batches into global `jax.Array`s sharded over
    the mesh's batch axes (the TPU replacement for `send_to_device`,
    ref operations.py:135, and the XLA `MpDeviceLoader`).

    Leaves whose leading dim can't shard (scalars / 0-d) are replicated.
    """
    if mesh is None:
        mesh = PartialState().mesh
    sharded, replicated, dp = _mesh_batch_layout(mesh, tuple(batch_axes))

    def _make(x):
        if isinstance(x, jax.Array) and jax.process_count() == 1:
            # already on device: reshard device->device (a no-op when the
            # layout matches) instead of round-tripping through the host.
            # Multi-host keeps the numpy path — assembling a global array
            # from per-host locals needs addressable host data.
            if x.ndim == 0 or x.shape[0] % dp != 0:
                return jax.device_put(x, replicated)
            return jax.device_put(x, sharded)
        x = _to_numpy(x)
        if not isinstance(x, np.ndarray):
            return x
        if x.ndim == 0 or (x.shape[0] * jax.process_count()) % dp != 0:
            if x.ndim > 0 and jax.process_count() > 1:
                # replicated sharding over divergent per-host data would build
                # a silently inconsistent "global" array — refuse loudly
                raise ValueError(
                    f"leading dim {x.shape[0]} x {jax.process_count()} hosts is "
                    f"not divisible by dp={dp}; pad the batch (see pad_batch_to) "
                    "before make_global_batch on multi-host runs"
                )
            sharding = replicated
        else:
            sharding = sharded
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(_make, batch)


def pad_batch_to(batch: Any, target: int, rows: int | None = None) -> Any:
    """Wraparound-pad every leaf's leading dim to `target` rows. Row
    containers (see `_is_row_container`) wraparound-extend too, so short-tail
    padding never leaves one rank with fewer rows than its peers. `rows` is
    the batch's current row count (disambiguates equal-length 1-D lists)."""

    def _pad(x):
        if _is_row_container(x, rows):
            if rows is None:
                # unknown row count: leave containers untouched — the
                # dispatcher path replicates list leaves, and recursing would
                # pad ragged token rows along the TOKEN dimension
                return x
            # only a container with exactly one entry per row is row data; a
            # short metadata list (e.g. label names) replicates untouched
            return _wrap_pad_rows(x, target) if len(x) == rows else x
        x = _to_numpy(x)
        if (
            rows is not None
            and isinstance(x, np.ndarray)
            and x.ndim > 0
            and x.shape[0] != rows
        ):
            return x  # aux array (e.g. per-class weights): not batch rows
        return _wrap_pad_rows(x, target)

    return jax.tree_util.tree_map(
        _pad, batch, is_leaf=lambda x: _is_row_container(x, rows)
    )


# ---------------------------------------------------------------------------
# loaders
# ---------------------------------------------------------------------------


class _PrefetchIterator:
    """Background-thread prefetch of a bounded number of prepared batches."""

    def __init__(self, source_iter: Iterator, prepare: Callable, depth: int):
        self._queue: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._error: BaseException | None = None
        self._stop = threading.Event()

        def worker():
            try:
                for item in source_iter:
                    payload = prepare(item)
                    if not self._put(payload):
                        return      # consumer closed mid-epoch
            except BaseException as e:  # surfaced on the consumer side
                self._error = e
            finally:
                self._put(_SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that a close() can always unpark: an abandoned
        iterator must not leave the worker blocked on a full queue
        forever (the epoch-break leak)."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is _SENTINEL:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the prefetch thread and reap it. Idempotent; safe to call
        with the source only partially consumed."""
        self._stop.set()
        while True:     # drain so a parked worker sees the stop promptly
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)


class DevicePrefetchIterator:
    """Keep up to `depth` batches' host->device transfers in flight ahead of
    the consumer (the device-side half of the input double buffer).

    `place` issues the async transfer (typically `make_global_batch`, i.e.
    `jax.device_put` onto the mesh `NamedSharding`); because JAX transfers are
    asynchronous, calling it here only *enqueues* the copy — batch i+1 (and
    deeper, up to `depth`) streams into HBM while the compiled step for batch
    i executes, so a steady-state step finds its input already resident
    instead of paying a synchronous host->device copy at dispatch time.

    `depth=2` is classic double buffering; deeper pipelines trade HBM for
    tolerance to jittery host-side batch times. ``depth`` is floored to 1 —
    this class IS the buffer, so it cannot express "no buffering"; to
    disable device-side prefetch entirely use the loader knob
    (``DataLoaderConfiguration.device_prefetch_depth = 0``), which bypasses
    this iterator and issues each transfer at hand-out time.
    """

    def __init__(self, source: Iterable, place: Callable, depth: int = 2):
        self._source = iter(source)
        self._place = place
        self._depth = max(1, int(depth))
        self._buffer: collections.deque = collections.deque()
        self._exhausted = False

    def __iter__(self):
        return self

    def _fill(self) -> None:
        while not self._exhausted and len(self._buffer) < self._depth:
            try:
                item = next(self._source)
            except StopIteration:
                self._exhausted = True
                return
            # span: the async transfer enqueue — on the trace timeline this
            # should be microseconds; a long slice here means the transfer
            # went synchronous (no-op when tracing is disabled)
            with span("data.prefetch_place"):
                self._buffer.append(self._place(item))

    def __next__(self):
        self._fill()
        if not self._buffer:
            raise StopIteration
        item = self._buffer.popleft()
        # enqueue the NEXT transfer before handing this batch out, so it is
        # in flight for the whole duration of the consumer's step
        self._fill()
        return item


class DataLoaderStateMixin:
    """end_of_dataloader / remainder bookkeeping hooked into GradientState
    (ref data_loader.py:355-390)."""

    def begin(self) -> None:
        self.end_of_dataloader = False
        self.remainder = -1
        self.tail_layout = None  # (num_hosts, padded_per_host, real_per_host)
        self.gradient_state._add_dataloader(self)

    def end(self) -> None:
        self.gradient_state._remove_dataloader(self)


class DataLoaderShard(DataLoaderStateMixin):
    """Wrap a per-host batch iterable; yield global sharded arrays
    (ref data_loader.py:391-517 `DataLoaderShard`).

    - one-batch-ahead detection of the final batch so `end_of_dataloader`
      is true *during* the last step (ref :445-476)
    - uneven final batch padded by wraparound; true sample count recorded in
      `remainder` for `gather_for_metrics`
    - per-epoch host RNG sync for torch/numpy-driven pipelines
    - device-side double buffering: host prep runs on the background thread,
      and up to `device_prefetch_depth` batches' async device transfers stay
      in flight ahead of the training step (`DevicePrefetchIterator`)
    """

    def __init__(
        self,
        loader: Iterable,
        mesh=None,
        batch_axes=BATCH_AXES,
        rng_types: list | None = None,
        put_on_device: bool = True,
        prefetch_size: int = 2,
        even_batches: bool = True,
        generator=None,
        device_prefetch_depth: int = 2,
    ):
        self.loader = loader
        self.mesh = mesh
        self.batch_axes = batch_axes
        self.rng_types = rng_types
        self.put_on_device = put_on_device
        self.prefetch_size = prefetch_size
        self.even_batches = even_batches
        self.generator = generator
        self.device_prefetch_depth = device_prefetch_depth
        self.gradient_state = GradientState()
        self.epoch = 0

    @property
    def total_batch_size(self) -> int | None:
        bs = getattr(self.loader, "batch_size", None)
        if bs is None:
            sampler = getattr(self.loader, "batch_sampler", None)
            bs = getattr(sampler, "batch_size", None)
        return bs

    @property
    def dp_size(self) -> int:
        mesh = self.mesh if self.mesh is not None else PartialState().mesh
        dp = 1
        for a in self.batch_axes:
            if a in mesh.axis_names:
                dp *= mesh.shape[a]
        return dp

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        for obj in (self.loader, getattr(self.loader, "sampler", None),
                    getattr(self.loader, "batch_sampler", None)):
            if obj is not None and hasattr(obj, "set_epoch"):
                obj.set_epoch(epoch)

    def _prepare_host(self, batch):
        """Host half of batch prep (runs on the background prefetch thread):
        numpy conversion + tail padding + remainder bookkeeping. No device
        work happens here — the transfer is issued by the consumer-side
        `DevicePrefetchIterator` so its depth (not the host queue's) bounds
        in-flight HBM."""
        with span("data.host_prep"):
            # device-resident leaves stay on device when they are about to
            # be placed anyway; pad_batch_to converts the rare uneven tail
            # itself
            batch = batch_to_numpy(
                batch, keep_device_arrays=self.put_on_device)
            n = _batch_size(batch)
            per_host = self.dp_size // jax.process_count()
            remainder = -1
            tail_layout = None
            if (
                self.even_batches
                and self.put_on_device
                and n is not None
                and n % per_host != 0
            ):
                target = math.ceil(n / per_host) * per_host
                # SPMD keeps per-host shapes identical, so every host sees the
                # same (n, target): global real count is n * num_hosts, and
                # after gathering, rows lay out as [host0: n real + pad,
                # host1: ...] — recorded so gather_for_metrics can drop pads
                # per host block.
                remainder = n * jax.process_count()
                tail_layout = (jax.process_count(), target, n)
                batch = pad_batch_to(batch, target, rows=n)
            return batch, remainder, tail_layout

    def _place(self, item):
        """Device half: issue the async transfer onto the mesh sharding."""
        batch, remainder, tail_layout = item
        with span("data.device_put"):
            placed = make_global_batch(batch, self.mesh, self.batch_axes)
        return placed, remainder, tail_layout

    def _prepare(self, batch):
        """Full prep for one batch (host + device) — kept as the single-shot
        path for callers that bypass the pipelined iterator."""
        item = self._prepare_host(batch)
        return self._place(item) if self.put_on_device else item

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.generator)
        self.begin()
        prefetch = None
        try:
            source = iter(self.loader)
            prefetch = prepared = _PrefetchIterator(
                source, self._prepare_host, self.prefetch_size
            )
            if self.put_on_device:
                if self.device_prefetch_depth > 0:
                    prepared = DevicePrefetchIterator(
                        prepared, self._place, self.device_prefetch_depth
                    )
                else:
                    prepared = map(self._place, prepared)
            current = next(prepared, _SENTINEL)
            while current is not _SENTINEL:
                nxt = next(prepared, _SENTINEL)
                batch, remainder, tail_layout = current
                if nxt is _SENTINEL:
                    self.end_of_dataloader = True
                    loader_rem = getattr(self.loader, "remainder", -1)
                    if remainder == -1:
                        # a sharding iterable below may have padded/duplicated
                        # the final round itself (ShardedBatchIterable)
                        remainder = loader_rem
                        tail_layout = getattr(self.loader, "tail_layout", None)
                    elif loader_rem != -1:
                        # both layers padded (batch size not divisible by the
                        # per-host device count AND hosts recycled batches) —
                        # the tail metadata can't express the combination, so
                        # exact gather_for_metrics dedup is off for this round
                        logger.warning(
                            "final batch was padded at both the host-sharding "
                            "and device-sharding layers; gather_for_metrics "
                            "cannot drop host-level duplicates. Use a batch "
                            "size divisible by per-host device count for "
                            "exact eval counts."
                        )
                    if remainder != -1:
                        self.remainder = remainder
                        self.tail_layout = tail_layout
                yield batch
                current = nxt
            self.set_epoch(self.epoch + 1)
        finally:
            # breaking out early must still reap the prefetch thread (an
            # abandoned epoch would leave it parked on the full queue) and
            # unregister from GradientState — a stale reference would
            # corrupt accumulate() sync decisions
            if prefetch is not None:
                prefetch.close()
            self.end()

    def __len__(self) -> int:
        return len(self.loader)  # type: ignore[arg-type]


class DataLoaderDispatcher(DataLoaderStateMixin):
    """Process 0 reads the underlying iterable; batches are broadcast to all
    hosts, then sliced per host and assembled into global arrays
    (ref data_loader.py:562-796 `DataLoaderDispatcher`). For streams that
    cannot be sharded at the source."""

    def __init__(
        self,
        loader: Iterable,
        mesh=None,
        batch_axes=BATCH_AXES,
        split_batches: bool = False,
        put_on_device: bool = True,
    ):
        self.loader = loader
        self.mesh = mesh
        self.batch_axes = batch_axes
        self.split_batches = split_batches
        self.put_on_device = put_on_device
        self.gradient_state = GradientState()
        self.state = PartialState()
        self.epoch = 0

    def _fetch_and_broadcast(self, source) -> tuple[Any, bool]:
        """Rank 0 nexts the iterator; everyone learns (batch, stop).

        With `split_batches=False` the reference fetches `num_processes`
        batches and concatenates so each process still sees a full batch
        (ref data_loader.py:618-680); with True, one batch is split.
        """
        if self.state.is_main_process:
            fetches = 1 if self.split_batches else self.state.num_processes
            parts = []
            for _ in range(fetches):
                batch = next(source, _SENTINEL)
                if batch is _SENTINEL:
                    break
                parts.append(batch_to_numpy(batch))
            if not parts:
                payload = [None, True]
            else:
                merged = parts[0] if len(parts) == 1 else concatenate(parts)
                payload = [merged, False]
        else:
            payload = [None, None]
        if self.state.num_processes > 1:
            payload = broadcast_object_list(payload, from_process=0)
        return payload[0], payload[1]

    def __iter__(self):
        self.begin()
        try:
            source = iter(self.loader) if self.state.is_main_process else iter(())
            current, stop = self._fetch_and_broadcast(source)
            while not stop:
                nxt, stop = self._fetch_and_broadcast(source)
                n = _batch_size(current)
                P = self.state.num_processes
                remainder = -1
                if n is not None and n % P != 0:
                    # pad to divisible (wraparound) instead of dropping tail
                    # rows; real count recorded for gather_for_metrics —
                    # dispatcher pads at the GLOBAL tail, so plain [:n]
                    # truncation is correct (no per-host layout needed)
                    target = math.ceil(n / P) * P
                    current = pad_batch_to(current, target, rows=n)
                    remainder = n
                    n = target
                # slice this host's shard of the global batch: arrays and
                # row containers with one entry per row slice; aux leaves
                # (short metadata lists, per-class weight arrays) replicate.
                # slice_tensors would recurse into ragged row lists and cut
                # each ROW along its token dimension instead.
                per_host = n // P if n else None
                if per_host is not None and P > 1:
                    start = self.state.process_index * per_host
                    sl = slice(start, start + per_host)
                    rows_now = n

                    def _shard(x):
                        if _is_row_container(x, rows_now):
                            return x[sl] if len(x) == rows_now else x
                        if getattr(x, "ndim", 0) and x.shape[0] == rows_now:
                            return x[sl]
                        return x

                    local = jax.tree_util.tree_map(
                        _shard, current,
                        is_leaf=lambda v: _is_row_container(v, rows_now),
                    )
                else:
                    local = current
                if stop:
                    self.end_of_dataloader = True
                    if remainder != -1:
                        self.remainder = remainder
                if self.put_on_device:
                    local = make_global_batch(local, self.mesh, self.batch_axes)
                yield local
                current = nxt
        finally:
            self.end()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)  # type: ignore[arg-type]


class SkipDataLoader:
    """Iterate skipping the first `skip_batches` batches
    (ref data_loader.py:1059)."""

    def __init__(self, loader: Iterable, skip_batches: int = 0):
        self.loader = loader
        self.skip_batches = skip_batches

    def __iter__(self):
        for index, batch in enumerate(self.loader):
            if index >= self.skip_batches:
                yield batch

    def __len__(self):
        return max(len(self.loader) - self.skip_batches, 0)  # type: ignore[arg-type]


def skip_first_batches(dataloader, num_batches: int = 0):
    """Mid-epoch resume (ref data_loader.py:1082-1149). Wraps the prepared
    loader's *source* so prefetch/global assembly still apply."""
    if isinstance(dataloader, (DataLoaderShard, DataLoaderDispatcher)):
        inner = SkipDataLoader(dataloader.loader, num_batches)
        import copy

        clone = copy.copy(dataloader)
        clone.loader = inner
        return clone
    return SkipDataLoader(dataloader, num_batches)


# ---------------------------------------------------------------------------
# prepare_data_loader (ref data_loader.py:797-1034)
# ---------------------------------------------------------------------------


def _looks_like_torch_loader(obj) -> bool:
    return (
        hasattr(obj, "dataset")
        and hasattr(obj, "batch_sampler")
        or type(obj).__name__ == "DataLoader"
    )


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: int | None = None,
    process_index: int | None = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: list | None = None,
    dispatch_batches: bool | None = None,
    even_batches: bool = True,
    use_seedable_sampler: bool = True,
    mesh=None,
    batch_axes=BATCH_AXES,
    config: DataLoaderConfiguration | None = None,
    prefetch_size: int | None = None,
    device_prefetch_depth: int | None = None,
):
    """Shard any batch iterable across hosts and emit global sharded arrays.

    Accepts a torch `DataLoader` (rebuilt around a `BatchSamplerShard` over
    its dataset — ref data_loader.py:887-1000), a plain iterable of batches,
    or an iterable dataset (wrapped in `IterableDatasetShard`).

    An explicit ``prefetch_size``/``device_prefetch_depth`` argument wins
    over ``config``; unset (None) falls back to the config (or its
    defaults). The prefetch knobs apply to the sharded path only — the
    dispatcher (``dispatch_batches=True``) is broadcast-driven and does not
    prefetch (eager rank-0 fetches would reorder its collectives against
    the training step's on multi-host worlds).
    """
    explicit_prefetch = (prefetch_size, device_prefetch_depth) != (None, None)
    if config is not None:
        split_batches = config.split_batches
        dispatch_batches = config.dispatch_batches
        even_batches = config.even_batches
        use_seedable_sampler = config.use_seedable_sampler
    if prefetch_size is None:
        prefetch_size = config.prefetch_size if config is not None else 2
    if device_prefetch_depth is None:
        device_prefetch_depth = (
            config.device_prefetch_depth if config is not None else 2
        )
    state = PartialState()
    num_processes = num_processes if num_processes is not None else state.num_processes
    process_index = process_index if process_index is not None else state.process_index

    if dispatch_batches:
        if explicit_prefetch:
            import warnings

            warnings.warn(
                "prefetch_size/device_prefetch_depth have no effect with "
                "dispatch_batches=True: the dispatcher is broadcast-driven "
                "and fetches in lockstep with the step loop.",
                stacklevel=2,
            )
        return DataLoaderDispatcher(
            dataloader,
            mesh=mesh,
            batch_axes=batch_axes,
            split_batches=split_batches,
            put_on_device=put_on_device,
        )

    loader = dataloader
    if num_processes > 1 and _looks_like_torch_loader(dataloader):
        loader = _reshard_torch_loader(
            dataloader, num_processes, process_index, split_batches, even_batches,
            use_seedable_sampler,
        )
    elif num_processes > 1 and hasattr(dataloader, "__iter__") and not hasattr(dataloader, "__len__"):
        loader = IterableDatasetShard(
            dataloader,
            batch_size=getattr(dataloader, "batch_size", 1) or 1,
            num_processes=num_processes,
            process_index=process_index,
            split_batches=split_batches,
        )
    elif num_processes > 1 and not getattr(dataloader, "is_host_sharded", False):
        # sized stream of ready-made batches: stride whole batches across
        # hosts, or slice each batch when split_batches is requested.
        # Sources that already shard per host (native.TokenCorpusLoader)
        # declare is_host_sharded and pass through untouched.
        loader = ShardedBatchIterable(
            dataloader, num_processes, process_index, even_batches=even_batches,
            split_batches=split_batches,
        )

    return DataLoaderShard(
        loader,
        mesh=mesh,
        batch_axes=batch_axes,
        rng_types=rng_types,
        put_on_device=put_on_device,
        even_batches=even_batches,
        prefetch_size=prefetch_size,
        device_prefetch_depth=device_prefetch_depth,
    )


def _reshard_torch_loader(
    dataloader, num_processes, process_index, split_batches, even_batches,
    use_seedable_sampler,
):
    """Rebuild a torch DataLoader over a host-sharded batch sampler, keeping
    collate_fn/num_workers (ref data_loader.py:887-1000)."""
    import torch.utils.data as tud

    batch_sampler = dataloader.batch_sampler
    if use_seedable_sampler and isinstance(
        getattr(dataloader, "sampler", None), tud.RandomSampler
    ):
        sampler = SeedableRandomSampler(len(dataloader.dataset))
        batch_sampler = tud.BatchSampler(
            sampler, batch_sampler.batch_size, batch_sampler.drop_last
        )
    sharded = BatchSamplerShard(
        batch_sampler,
        num_processes=num_processes,
        process_index=process_index,
        split_batches=split_batches,
        even_batches=even_batches,
    )
    return tud.DataLoader(
        dataloader.dataset,
        batch_sampler=sharded,
        collate_fn=dataloader.collate_fn,
        num_workers=dataloader.num_workers,
        pin_memory=False,
    )
