"""Pallas paged-attention decode kernel: walk the page table IN the kernel.

The serving engine's decode step used to gather every slot's pages into a
dense [L, S, rows, H, D] view (`serving/cache.py paged_batch_view`)
*before* the vmapped family forward — O(pool) HBM reads per token,
rebuilt outside the attention op, growing with `pages_per_slot` however
short the live sequences are. This kernel inverts that: the pool stays
in place in HBM and the page table drives the kernel's BlockSpec index
maps (scalar prefetch), so each grid step stages exactly ONE page of one
slot's K/V into VMEM — pages are read once, where they live, and only a
slot's *live* pages are visited (dead table entries re-map to an
already-fetched block, so Mosaic's pipeline revisit elides the fetch).
The pjit/TPUv4 rule (arxiv 2204.06514) still holds: the table and
lengths are traced *data*, so one compiled program covers every page
mapping, request mix, and eviction history.

Layout and semantics:

- pool K/V: [num_pages + 1, page_size, Hkv, D] per layer (the serving
  pool minus its leading layer dim — the kernel is called inside the
  family forward's `lax.scan` over layers). The last page is the
  reserved trash page backing padded table entries.
- page table: [slots, pages_per_slot] int32; lengths: [slots] int32.
- q: one token per slot, GQA grouped as [slots, Hkv, group, D] — the
  head-group broadcast happens in-kernel (each grid step dots the whole
  q group against its kv head's page), so K/V are never `repeat_kv`'d.
- the NEW token's K/V (this step's, position == length) are folded into
  the online softmax as a final single-key update instead of being
  written to the pool first: the kernel never writes, the engine
  scatters the one new row per slot afterwards (`paged_append_rows`).
- int8 pools (`PagedKV.scales` set) dequantize per page INSIDE the
  kernel — codes * per-row-per-head scales — so the HBM stream is the
  int8 bytes, not a pre-dequantized bf16 copy.

Masking matches `models/decode.cached_attention_mask` exactly: a slot's
query (position == length) attends pool rows < length plus its own new
K/V; `window` applies the HF sliding-window band (key visible iff
q - key < window). Retired slots (all-trash tables, stale lengths)
compute garbage that the engine discards via its `live` lane mask —
same contract as the dense gather path.

On non-TPU backends the kernel runs in pallas interpret mode (slow, for
tests) — tier-1 proves exactness against `paged_decode_reference` and
token-exactness against the dense-gather engine path on CPU.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# `TPUCompilerParams` was renamed `CompilerParams` in newer jax; accept both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

NEG_INF = -1e30
_LANES = 128  # TPU vector lane width; scalar-per-group state is kept 2D

__all__ = [
    "PagedKV",
    "PagedDecodeMeta",
    "paged_decode_attention",
    "paged_decode_reference",
]


# ---------------------------------------------------------------------------
# the engine <-> family interface types
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class PagedKV:
    """One pool buffer (K or V) as it threads through a family forward.

    `data` is the [L, pages+1, page_size, Hkv, D] pool (or a per-layer
    slice of it — `lax.scan` over the leading dim slices both children
    together); `scales` is the int8 mode's [L, pages+1, page_size, Hkv]
    per-row-per-head scale array, None for a bf16 pool. `compute_dtype`
    is the dtype attention math materializes K/V rows in (and the dtype
    of the new-token rows handed back for the engine to write); None
    defaults to `data.dtype` (bf16 pools) or bfloat16 (int8 pools).

    The `is_paged_kv` marker lets `models/decode.decode_attention`
    dispatch without importing this (pallas-importing) module on the
    dense path."""

    is_paged_kv = True

    def __init__(self, data, scales=None, compute_dtype=None):
        self.data = data
        self.scales = scales
        self.compute_dtype = compute_dtype

    @property
    def quantized(self) -> bool:
        return self.scales is not None

    @property
    def row_dtype(self):
        """The dtype K/V rows materialize in (see class docstring)."""
        if self.compute_dtype is not None:
            return self.compute_dtype
        return jnp.bfloat16 if self.quantized else self.data.dtype

    def tree_flatten(self):
        return (self.data, self.scales), (self.compute_dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scales = children
        return cls(data, scales, compute_dtype=aux[0])


@jax.tree_util.register_pytree_node_class
class PagedDecodeMeta:
    """The paged decode step's per-slot addressing, riding the family
    cache tuple's third slot (where the dense path carries `cache_len`).

    `table` [slots, pages_per_slot] int32 and `lengths` [slots] int32 are
    traced data; `rows` (pages_per_slot * page_size, static) is what
    `rope_table_len` sizes the rotary tables by. Families advance the
    dense `cache_len` with `+ seq_len` when returning new caches —
    `__add__` absorbs that as a no-op: per-slot length advance is the
    engine's job (live-lane masked, in `paged_append_rows`), not the
    traced program's."""

    is_paged_meta = True

    def __init__(self, table, lengths, rows: int):
        self.table = table
        self.lengths = lengths
        self.rows = rows

    def __add__(self, other):
        return self

    def tree_flatten(self):
        return (self.table, self.lengths), (self.rows,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        table, lengths = children
        return cls(table, lengths, rows=aux[0])


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _paged_decode_kernel(table_ref, lengths_ref, q_ref, kn_ref, vn_ref,
                         pk_ref, pv_ref, *rest, sm_scale: float,
                         page_size: int, pages_per_slot: int,
                         window: int | None, quantized: bool):
    """Grid [slots, Hkv, pages_per_slot] (pages innermost/arbitrary):
    each step folds one page of one slot's kv head into the online
    softmax; the last step also folds the new token's K/V and finalizes.
    `table_ref`/`lengths_ref` are scalar-prefetch SMEM refs — the same
    values the BlockSpec index maps used to choose the page blocks."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        (ks_ref, vs_ref), (o_ref, m_scr, l_scr, acc_scr) = (None, None), rest
    s, j = pl.program_id(0), pl.program_id(2)
    length = lengths_ref[s]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def update(s_blk, v_blk):
        """One online-softmax step: fold pre-scaled, pre-masked scores
        s_blk [G, n] and values v_blk [n, D] into the running state.
        Probabilities stay f32 through the PV dot — decode is
        bandwidth-bound, not MXU-bound, and the dense reference path
        keeps f32 probabilities too."""
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
        p = jnp.exp(s_blk - m_new)
        # a fully-masked block keeps m_new at NEG_INF where exp(s - m)
        # would be exp(0) = 1 per masked key — zero those explicitly
        p = jnp.where(s_blk <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # a page is live iff it holds at least one row below the slot's
    # length; dead pages (allocation slack, trash padding) compute
    # nothing, and their index map re-targeted an already-fetched block
    live = j * page_size < length

    @pl.when(live)
    def _page():
        q = q_ref[0, 0].astype(jnp.float32)           # [G, D]
        k = pk_ref[0, :, 0, :]                        # [ps, D]
        v = pv_ref[0, :, 0, :]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0, :, 0].astype(
                jnp.float32)[:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0].astype(
                jnp.float32)[:, None]
        s_blk = jnp.dot(q, k.T.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * sm_scale
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        keep = pos < length
        if window is not None:
            # HF sliding-window convention: key visible iff q - key <
            # window; the query sits at position == length
            keep = keep & (pos > length - window)
        s_blk = jnp.where(keep, s_blk, NEG_INF)
        update(s_blk, v)

    @pl.when(j == pages_per_slot - 1)
    def _tail():
        # the new token's K/V (position == length, always visible — its
        # window distance is 0) folds as one more single-key update;
        # then finalize. l > 0 always: this key contributes exp(0) when
        # it is the running max.
        q = q_ref[0, 0].astype(jnp.float32)
        kn = kn_ref[0, 0].astype(jnp.float32)          # [D]
        s_new = jnp.dot(q, kn[:, None],
                        preferred_element_type=jnp.float32) * sm_scale
        update(s_new, vn_ref[0, 0][None, :])
        l = l_scr[...][:, :1]
        o_ref[0, 0] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)


def _paged_attention_call(q4, kn, vn, pool_k, pool_v, k_scales, v_scales,
                          table, lengths, window: int | None,
                          interpret: bool):
    """q4 [S, Hkv, G, D], kn/vn [S, Hkv, D], pool [N+1, ps, Hkv, D]
    (+ scales [N+1, ps, Hkv] when quantized) -> out [S, Hkv, G, D]."""
    S, Hkv, G, D = q4.shape
    P = table.shape[1]
    ps = pool_k.shape[1]
    quantized = k_scales is not None
    sm_scale = 1.0 / math.sqrt(D)

    def page_map(s, h, j, table_ref, lengths_ref):
        # dead steps (page start >= length) re-target page 0 of the
        # slot's table: consecutive dead steps then revisit one block
        # instead of streaming allocation slack / trash padding
        j_live = jnp.where(j * ps < jnp.maximum(lengths_ref[s], 1), j, 0)
        return table_ref[s * P + j_live], 0, h, 0

    def per_slot(s, h, j, table_ref, lengths_ref):
        return (s, h, 0, 0)

    def per_head_row(s, h, j, table_ref, lengths_ref):
        return (s, h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, G, D), per_slot),
        pl.BlockSpec((1, 1, D), per_head_row),
        pl.BlockSpec((1, 1, D), per_head_row),
        pl.BlockSpec((1, ps, 1, D), page_map),
        pl.BlockSpec((1, ps, 1, D), page_map),
    ]
    operands = [q4, kn, vn, pool_k, pool_v]
    if quantized:
        scale_map = (lambda s, h, j, table_ref, lengths_ref:
                     page_map(s, h, j, table_ref, lengths_ref)[:3])
        in_specs += [pl.BlockSpec((1, ps, 1), scale_map),
                     pl.BlockSpec((1, ps, 1), scale_map)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, Hkv, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, D), per_slot),
        scratch_shapes=[
            pltpu.VMEM((G, _LANES), jnp.float32),
            pltpu.VMEM((G, _LANES), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, sm_scale=sm_scale, page_size=ps,
        pages_per_slot=P, window=window, quantized=quantized)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((S, Hkv, G, D), q4.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(table.reshape(-1), lengths, *operands)


# ---------------------------------------------------------------------------
# the op the shared decode path calls
# ---------------------------------------------------------------------------


def paged_decode_attention(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    pk: PagedKV,
    pv: PagedKV,
    meta: PagedDecodeMeta,
    window: int | None = None,
    interpret: bool | None = None,
):
    """One decode step of paged attention for every slot at once.

    q: [S, 1, H, D] (S slots, one token each, H = Hkv * group);
    k_new/v_new: [S, 1, Hkv, D] — this step's K/V, folded in-kernel and
    returned (cast to the pool's row dtype) for the engine to append.
    Returns (out [S, 1, H, D], (k_row, v_row) both [S, 1, Hkv, D])."""
    S, sq, H, D = q.shape
    if sq != 1:
        raise ValueError(
            f"paged decode attention is one token per slot; got S_q={sq} "
            "(chunked prefill stays on the dense-gather path)")
    Hkv = k_new.shape[2]
    if H % Hkv:
        raise ValueError(f"q heads ({H}) not a multiple of kv heads ({Hkv})")
    if meta.table.shape[0] != S:
        raise ValueError(
            f"page table covers {meta.table.shape[0]} slots, q has {S}")
    if window is not None and (window <= 0 or window >= meta.rows):
        window = None  # band wider than the cache reach: plain causal
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    G = H // Hkv
    row_dtype = pk.row_dtype
    # the fold must see exactly the bytes the engine will write, so a
    # later step reading the row from the pool agrees with this step
    k_row = k_new.astype(row_dtype)
    v_row = v_new.astype(row_dtype)
    q4 = q[:, 0].reshape(S, Hkv, G, D)
    out = _paged_attention_call(
        q4, k_row[:, 0], v_row[:, 0], pk.data, pv.data, pk.scales,
        pv.scales, meta.table, meta.lengths, window, interpret)
    return out.reshape(S, 1, H, D), (k_row, v_row)


def paged_decode_reference(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    pk: PagedKV,
    pv: PagedKV,
    meta: PagedDecodeMeta,
    window: int | None = None,
):
    """Dense-gather reference with identical semantics (and the
    executable spec of them): gather every table page, dequantize,
    overlay the new token's row at position == length, mask rows the
    query may not see, plain f32 softmax. The exactness tests pin the
    kernel to this; the serving engine's dense path is the same math
    threaded through the family forward."""
    S, _, H, D = q.shape
    Hkv = k_new.shape[2]
    G = H // Hkv
    ps = pk.data.shape[1]
    R = meta.table.shape[1] * ps
    row_dtype = pk.row_dtype

    def dense(p: PagedKV):
        pages = p.data[meta.table]                      # [S, P, ps, Hkv, D]
        full = pages.astype(jnp.float32)
        if p.quantized:
            full = full * p.scales[meta.table].astype(jnp.float32)[..., None]
        return full.reshape(S, R, Hkv, D)

    k_all, v_all = dense(pk), dense(pv)
    k_row = k_new.astype(row_dtype)
    v_row = v_new.astype(row_dtype)
    rows = jnp.arange(R, dtype=jnp.int32)
    sel = (rows[None, :] == meta.lengths[:, None])[:, :, None, None]
    k_all = jnp.where(sel, k_row.astype(jnp.float32), k_all)
    v_all = jnp.where(sel, v_row.astype(jnp.float32), v_all)
    keep = rows[None, :] <= meta.lengths[:, None]
    if window is not None and window < R:
        keep = keep & (rows[None, :] > meta.lengths[:, None] - window)
    q4 = q[:, 0].reshape(S, Hkv, G, D).astype(jnp.float32)
    s = jnp.einsum("shgd,srhd->shgr", q4, k_all) / math.sqrt(D)
    s = jnp.where(keep[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shgr,srhd->shgd", p, v_all)
    return out.reshape(S, 1, H, D).astype(q.dtype), (k_row, v_row)
