"""Pallas flash attention (TPU).

Blockwise attention with online softmax: O(S) memory instead of the S x S
score matrix. No reference equivalent — the reference delegates attention to
torch/bnb kernels; this is part of the long-context answer (SURVEY.md §5)
together with parallel/ring_attention.py.

Forward is a pallas kernel with grid [batch*heads, q_blocks, k_blocks]
(k innermost): each step stages only (block_q, d) of Q and (block_k, d) of
K/V into VMEM — VMEM use is O(block), not O(S), so 32k+ contexts fit — and
carries the online-softmax state (running max / sum / accumulator) in VMEM
scratch across the k dimension. Causal variant no-ops fully masked k blocks
via `pl.when`. Backward is fused too (FlashAttention-2): the forward saves
only O and the per-row logsumexp; a dQ kernel (k innermost) and a dK/dV
kernel (q innermost) recompute the probability blocks on the fly, so both
directions are O(S) memory — no S x S score matrix anywhere.

On non-TPU backends the kernel runs in pallas interpret mode (slow, for
tests); prefer `dot_product_attention` there.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# `TPUCompilerParams` was renamed `CompilerParams` in newer jax; accept both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

NEG_INF = -1e30
_LANES = 128  # TPU vector lane width; scalar-per-row state is kept 2D
_SUB = 8      # minimal lane width Mosaic accepts for a full-dim block: the
              # LSE rides as [BH, S, 8] (16x smaller than lane-broadcast)


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (0 for n < 1)."""
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


def _mask_spec(heads: int, block_k: int, swap_grid: bool = False):
    """BlockSpec for the [B, SUB, S_k] key-padding mask: one copy per batch
    row, shared across `heads` heads via the index map. `swap_grid` matches
    the dK/dV kernel whose grid is (bh, k_blocks, q_blocks)."""
    if swap_grid:
        return pl.BlockSpec((1, _SUB, block_k),
                            lambda b, j, i: (b // heads, 0, j))
    return pl.BlockSpec((1, _SUB, block_k),
                        lambda b, i, j: (b // heads, 0, j))


def _apply_key_mask(mask_ref, s):
    """NEG_INF-out masked keys; mask block is [1, SUB, bk], one sublane row
    broadcasts over the q rows of s."""
    return jnp.where(mask_ref[0][:1, :] > 0, s, NEG_INF)


def _band_live(qi, ki, block_q, block_k, causal, window):
    """Whether k block `ki` can contribute to q block `qi`: under causality
    its first key must be visible to the block's last query; under a sliding
    window its last key must be inside the reach of the block's first query
    (key > q - window)."""
    live = (qi + 1) * block_q - 1 >= ki * block_k if causal else True
    if window is not None:
        live = live & (ki * block_k + block_k - 1 > qi * block_q - window)
    return live


def _band_mask(s, qi, ki, block_q, block_k, causal, window):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = q_pos >= k_pos if causal else jnp.bool_(True)
    if window is not None:
        # HF sliding-window convention: key visible iff q - key < window
        # (reach of `window` positions INCLUDING the query itself)
        keep = keep & (q_pos - k_pos < window)
    return jnp.where(keep, s, NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, *rest, causal: bool,
                  sm_scale: float, block_q: int, block_k: int,
                  num_k_blocks: int, with_lse: bool = False,
                  with_mask: bool = False, window: int | None = None):
    if with_mask:
        mask_ref, o_ref, *rest = rest
    else:
        mask_ref, (o_ref, *rest) = None, rest
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        (lse_ref,), (m_scr, l_scr, acc_scr) = (None,), rest
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = _band_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        # dots run on native (bf16) inputs with f32 accumulation: full MXU
        # rate on v5e/v5p (f32 matmul is 4x slower); softmax state stays f32
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal or window is not None:
            s = _band_mask(s, qi, ki, block_q, block_k, causal, window)
        if mask_ref is not None:
            s = _apply_key_mask(mask_ref, s)
        m_prev = m_scr[...][:, :1]  # [bq, 1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask_ref is not None or window is not None:
            # a row with nothing visible in any block so far keeps m_new at
            # NEG_INF, where exp(s - m_new) would be exp(0)=1 per masked key
            # (a windowed live block can have rows entirely out of band) —
            # zero those explicitly
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        o_ref[0] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp per row, lane-broadcast (the TPU-friendly layout the
            # backward kernels read without transposes). Fully-masked rows
            # (l == 0) pin lse to 0 so the backward's exp(s - lse) stays 0
            # instead of exp(NEG_INF - NEG_INF) garbage.
            lse = m_scr[...][:, :1] + jnp.log(jnp.maximum(l, 1e-30))
            lse = jnp.where(l > 0, lse, 0.0)
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool, save_residuals: bool = False, mask=None,
                   heads: int = 1, window: int | None = None):
    """q,k,v: [BH, S, D] -> [BH, S, D] (and LSE [BH, S, 8] if asked).
    mask: optional [B, SUB, S_k] key-padding mask (1 = attend), sublane-
    broadcast like the LSE residual and shared across `heads` heads via the
    index map (one HBM copy per batch row, not per head)."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    num_k_blocks = seq_k // block_k
    grid = (bh, seq_q // block_q, num_k_blocks)
    kernel = functools.partial(
        _flash_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k_blocks,
        with_lse=save_residuals, with_mask=mask is not None, window=window,
    )
    out_shape = [jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))]
    if save_residuals:
        out_shape.append(jax.ShapeDtypeStruct((bh, seq_q, _SUB), jnp.float32))
        out_specs.append(pl.BlockSpec((1, block_q, _SUB), lambda b, i, j: (b, i, 0)))
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if mask is not None:
        in_specs.append(_mask_spec(heads, block_k))
        operands.append(mask)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    if save_residuals:
        return res[0], res[1]
    return res[0]


def _flash_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
                     causal: bool, sm_scale: float, block_q: int,
                     block_k: int, num_k_blocks: int,
                     with_mask: bool = False, window: int | None = None):
    """FlashAttention-2 backward, dQ pass: grid [BH, q_blocks, k_blocks]."""
    if with_mask:
        mask_ref, dq_ref, dq_scr = rest
    else:
        mask_ref, (dq_ref, dq_scr) = None, rest
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = _band_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        # native-dtype (bf16) MXU dots with f32 accumulation; f32-only for
        # the softmax state and elementwise math
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        # per-row state: lse block is (1, bq, 8) -> column [bq, 1]; delta
        # recomputed from O/dO blocks (cheap elementwise, no HBM buffer)
        lse = lse_ref[0][:, :1]
        delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                        axis=-1, keepdims=True)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal or window is not None:
            s = _band_mask(s, qi, ki, block_q, block_k, causal, window)
        if mask_ref is not None:
            s = _apply_key_mask(mask_ref, s)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] += sm_scale * jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
                      causal: bool, sm_scale: float, block_q: int,
                      block_k: int, num_q_blocks: int,
                      with_mask: bool = False, window: int | None = None):
    """FlashAttention-2 backward, dK/dV pass: grid [BH, k_blocks, q_blocks]."""
    if with_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        mask_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = None, rest
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = _band_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        # native-dtype (bf16) MXU dots with f32 accumulation
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                        axis=-1, keepdims=True)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal or window is not None:
            s = _band_mask(s, qi, ki, block_q, block_k, causal, window)
        if mask_ref is not None:
            s = _apply_key_mask(mask_ref, s)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # contract over the q dim without materializing transposes
        # (dot_general; MXU takes either operand order)
        contract_q = (((0,), (0,)), ((), ()))
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, contract_q,
            preferred_element_type=jnp.float32)
        dk_scr[...] += sm_scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, contract_q,
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, causal: bool, block_q: int,
                    block_k: int, interpret: bool, mask=None,
                    heads: int = 1, window: int | None = None):
    """Fused O(S) backward: no S x S materialization.

    Per-row state stays near-compact: the saved residual is [BH, S] f32,
    re-broadcast transiently to [BH, S, 8] here (Mosaic's narrowest legal
    full-dim lane block); delta is recomputed inside the kernels from the
    O/dO blocks — no [BH, S, LANES] buffers in HBM."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    num_q_blocks = seq_q // block_q
    num_k_blocks = seq_k // block_k

    lse = jnp.broadcast_to(lse[..., None], (bh, seq_q, _SUB))

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, _SUB), lambda b, i, j: (b, i, 0))
    kq_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))

    dq_in_specs = [q_spec, kq_spec, kq_spec, q_spec, q_spec, row_spec]
    dq_operands = [q, k, v, o, do, lse]
    if mask is not None:
        dq_in_specs.append(_mask_spec(heads, block_k))
        dq_operands.append(mask)
    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, num_k_blocks=num_k_blocks,
            with_mask=mask is not None, window=window,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        grid=(bh, num_q_blocks, num_k_blocks),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_operands)

    # dK/dV pass: k blocks outer (parallel), q blocks inner (reduction)
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    row_spec2 = pl.BlockSpec((1, block_q, _SUB), lambda b, j, i: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    dkv_in_specs = [q_spec2, k_spec2, k_spec2, q_spec2, q_spec2, row_spec2]
    dkv_operands = [q, k, v, o, do, lse]
    if mask is not None:
        dkv_in_specs.append(_mask_spec(heads, block_k, swap_grid=True))
        dkv_operands.append(mask)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, num_q_blocks=num_q_blocks,
            with_mask=mask is not None, window=window,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        grid=(bh, num_k_blocks, num_q_blocks),
        in_specs=dkv_in_specs,
        out_specs=[k_spec2, k_spec2],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, window):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                          window=window)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, window):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                            save_residuals=True, window=window)
    # keep only one lane of the broadcast LSE as the saved residual
    # ([BH, S] f32, not [BH, S, 128]) — re-broadcast transiently in bwd
    return o, (q, k, v, o, lse[..., 0])


def _flash_bwd(causal, block_q, block_k, interpret, window, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k,
                           interpret, window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_masked(q, k, v, mask, causal, block_q, block_k, interpret, heads,
                  window):
    """Masked variant: mask is [B, SUB, S_k] (1 = attend), nondifferentiable
    data threaded as a regular operand (its cotangent is zeros) and shared
    across heads by the kernels' index maps."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                          mask=mask, heads=heads, window=window)


def _flash_masked_fwd(q, k, v, mask, causal, block_q, block_k, interpret,
                      heads, window):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                            save_residuals=True, mask=mask, heads=heads,
                            window=window)
    return o, (q, k, v, o, lse[..., 0], mask)


def _flash_masked_bwd(causal, block_q, block_k, interpret, heads, window,
                      res, g):
    q, k, v, o, lse, mask = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal, block_q,
                                 block_k, interpret, mask=mask, heads=heads,
                                 window=window)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    mask: jax.Array | None = None,
    window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """[B, S, H, D] flash attention, fused forward AND backward. Heads must
    already be repeated (GQA: call models.common.repeat_kv first). Block
    sizes are clamped to power-of-two divisors of the sequence where needed:
    causal self-attention at a non-block-multiple length runs the kernel on
    unpadded pow2-divisor blocks when they stay >= 256, else pads to a block
    multiple (causally exact) and slices; non-causal shrinks blocks to the
    largest pow2 divisor of the length. Only lengths whose usable block
    would drop under 16 rows (Mosaic sublane floor) — e.g. s < 16, or
    non-causal odd lengths — fall back to einsum attention.

    `mask` is a key-padding mask — [B, S_k] (or any shape squeezable to it,
    e.g. [B, 1, 1, S_k]) with 1/True = attend — applied inside the kernel in
    forward and backward; fully-masked rows produce zero output. Full
    per-position [B, ..., S_q, S_k] masks fall back to einsum attention.

    `window` is a sliding-attention window in the HF Mistral convention —
    key visible iff q - key < window (reach includes the query) — applied as
    a band mask inside the kernels; blocks wholly outside the band are
    skipped entirely, so long-context windowed attention costs
    O(S * window), not O(S^2). Requires causal=True.

    Default blocks come from the v5e sweep (benchmarks/sweep_attn.py):
    big blocks amortize pallas grid overhead — 512x1024 wins to ~2k context,
    1024x1024 from 4k up (96.7 TF/s vs einsum's 18.2 at s=4096)."""
    b, sq, h, d = q.shape
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding-window "
                             "attention is a causal-LM feature)")
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        if window >= k.shape[1]:
            window = None  # band wider than the sequence: plain causal
    sk = k.shape[1]
    key_mask = None
    if mask is not None:
        m = mask
        while m.ndim > 2 and m.shape[1] == 1:
            m = m[:, 0]
        if m.ndim == 2 and m.shape == (b, sk):
            key_mask = m
        else:
            from ..models.common import dot_product_attention

            return dot_product_attention(q, k, v, mask=mask, causal=causal,
                                         window=window)
    if block_q is None:
        block_q = 1024 if sq >= 4096 else 512
    if block_k is None:
        block_k = 1024
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    # clamp blocks to the sequence, rounded down to a power of two (>= 16 for
    # Mosaic sublane tiling): an unaligned block (e.g. 300 rows after a plain
    # min()) fails Mosaic lowering on real TPUs even though interpret-mode
    # tests would pass, and a non-power-of-two block (e.g. 528) would make
    # the lcm pad target below explode to ~32x the sequence
    block_q = _pow2_floor(min(block_q, sq))
    block_k = _pow2_floor(min(block_k, sk))

    def _fallback():
        from ..models.common import dot_product_attention

        return dot_product_attention(q, k, v, mask=key_mask, causal=causal,
                                     window=window)

    # sq != sk would make the kernel's top-aligned causal mask disagree with
    # the bottom-aligned reference (and read past the k buffer when sq > sk)
    if block_q < 16 or block_k < 16 or (causal and sq != sk):
        return _fallback()
    if sq % block_q or sk % block_k:
        if causal:
            # first preference: shrink to power-of-two divisor blocks and run
            # unpadded — s=1280 runs at 256-blocks instead of padding to 2048
            bq2, bk2 = min(block_q, sq & -sq), min(block_k, sk & -sk)
            if bq2 >= 256 and bk2 >= 256:
                block_q, block_k = bq2, bk2
            else:
                # pad to a block multiple and slice the result: causally
                # exact, since padded keys (index >= sq) are only visible to
                # padded queries — the training loss slices inputs to S-1,
                # which would otherwise dodge the kernel entirely. Equal
                # blocks keep the lcm (= block_q) and so the pad under one
                # block's worth.
                block_k = min(block_k, block_q)
                multiple = math.lcm(block_q, block_k)
                target = -(-sq // multiple) * multiple
                pad = target - sq
                qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
                kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
                vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
                mp = (
                    jnp.pad(key_mask, ((0, 0), (0, pad)))
                    if key_mask is not None else None
                )
                out = flash_attention(qp, kp, vp, causal=True, mask=mp,
                                      window=window, block_q=block_q,
                                      block_k=block_k, interpret=interpret)
                return out[:, :sq]
        else:
            # non-causal can't pad (extra keys would get real softmax
            # weight); shrink to the largest power-of-two divisor of the
            # length so e.g. s=1920 (divisible by 128, not 512) still runs
            block_q = min(block_q, sq & -sq)
            block_k = min(block_k, sk & -sk)
            if block_q < 16 or block_k < 16:
                return _fallback()
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    if key_mask is not None:
        # [B, SUB, S_k] layout: Mosaic needs the sublane dim of a block to
        # be a multiple of 8 (same trick as the LSE residual); one copy per
        # batch row, shared across heads by the kernels' index maps
        # f32, not bf16: Mosaic's vector compare doesn't lower for bf16
        mf = jnp.broadcast_to(
            key_mask.astype(jnp.float32)[:, None, :], (b, _SUB, sk)
        )
        out = _flash_masked(qf, kf, vf, mf, causal, block_q, block_k,
                            interpret, h, window)
    else:
        out = _flash(qf, kf, vf, causal, block_q, block_k, interpret, window)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
