"""Pallas flash attention (TPU).

Blockwise attention with online softmax: O(S) memory instead of the S x S
score matrix. No reference equivalent — the reference delegates attention to
torch/bnb kernels; this is part of the long-context answer (SURVEY.md §5)
together with parallel/ring_attention.py.

Forward is a pallas kernel (grid over [batch*heads, q_blocks], fori_loop over
k blocks with running max/sum in VMEM scratch; causal variant skips fully
masked k blocks). Backward is a custom_vjp that recomputes attention with the
XLA einsum path — correct everywhere, O(S^2) only in the backward; a pallas
backward kernel is a planned optimization.

On non-TPU backends the kernel runs in pallas interpret mode (slow, for
tests); prefer `dot_product_attention` there.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  sm_scale: float, block_q: int, seq_k: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, d]
    d = q.shape[-1]

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_k // block_k
    if causal:
        # q rows in this block end at (qi+1)*block_q - 1: k blocks beyond
        # that are fully masked — skip them entirely
        last_block = jax.lax.div((qi + 1) * block_q - 1, block_k) + 1
    else:
        last_block = num_k_blocks

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, last_block, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    """q,k,v: [BH, S, D] -> [BH, S, D]."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale,
        block_q=block_q, seq_k=seq_k,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


def _reference_attention(q, k, v, causal):
    """XLA einsum attention on [BH, S, D] (backward recompute path)."""
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32)
    s = s / math.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v, preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """[B, S, H, D] flash attention. Heads must already be repeated (GQA:
    call models.common.repeat_kv first). Sequence lengths must divide the
    block sizes; shorter sequences fall back to einsum attention."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # sq != sk would make the kernel's top-aligned causal mask disagree with
    # the bottom-aligned reference (and read past the k buffer when sq > sk)
    if sq % block_q or sk % block_k or (causal and sq != sk):
        from ..models.common import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash(qf, kf, vf, causal, block_q, block_k, interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
