"""Pallas flash attention (TPU).

Blockwise attention with online softmax: O(S) memory instead of the S x S
score matrix. No reference equivalent — the reference delegates attention to
torch/bnb kernels; this is part of the long-context answer (SURVEY.md §5)
together with parallel/ring_attention.py.

Forward is a pallas kernel with grid [batch*heads, q_blocks, k_blocks]
(k innermost): each step stages only (block_q, d) of Q and (block_k, d) of
K/V into VMEM — VMEM use is O(block), not O(S), so 32k+ contexts fit — and
carries the online-softmax state (running max / sum / accumulator) in VMEM
scratch across the k dimension. Causal variant no-ops fully masked k blocks
via `pl.when`. Backward is a custom_vjp that recomputes attention with the
XLA einsum path — correct everywhere, O(S^2) only in the backward; a pallas
backward kernel is a planned optimization.

On non-TPU backends the kernel runs in pallas interpret mode (slow, for
tests); prefer `dot_product_attention` there.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # TPU vector lane width; scalar-per-row state is kept 2D


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal: bool, sm_scale: float, block_q: int, block_k: int,
                  num_k_blocks: int):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: this k block contributes iff its first position is visible to
    # the last q row of the block
    live = (qi + 1) * block_q - 1 >= ki * block_k if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[...][:, :1]  # [bq, 1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        o_ref[0] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    """q,k,v: [BH, S, D] -> [BH, S, D]."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    num_k_blocks = seq_k // block_k
    grid = (bh, seq_q // block_q, num_k_blocks)
    kernel = functools.partial(
        _flash_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k_blocks,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _reference_attention(q, k, v, causal):
    """XLA einsum attention on [BH, S, D] (backward recompute path)."""
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32)
    s = s / math.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v, preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """[B, S, H, D] flash attention. Heads must already be repeated (GQA:
    call models.common.repeat_kv first). Sequence lengths must divide the
    block sizes; shorter sequences fall back to einsum attention."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # sq != sk would make the kernel's top-aligned causal mask disagree with
    # the bottom-aligned reference (and read past the k buffer when sq > sk)
    if sq % block_q or sk % block_k or (causal and sq != sk):
        from ..models.common import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash(qf, kf, vf, causal, block_q, block_k, interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
