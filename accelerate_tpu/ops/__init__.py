"""Custom TPU ops (pallas kernels + XLA fallbacks)."""

from .flash_attention import flash_attention
