"""Custom TPU ops (pallas kernels + XLA fallbacks)."""

from .flash_attention import flash_attention
from .quant import (
    QuantizedTensor,
    dequantize,
    dequantize_params,
    quantize,
    quantize_params,
    quantized_matmul,
)
from .fp8 import Fp8Meta, fp8_dot, init_fp8_state
