"""Native fp8 training path (transformer-engine replacement).

Replaces ref utils/transformer_engine.py (84 LoC `convert_model` swapping
nn.Linear for te.Linear). The torch/TE recipe — E4M3 forward / E5M2 backward,
per-tensor scales from a rolling amax history ("delayed scaling") — is kept,
but expressed functionally: `Fp8Meta` pytree state threads through the train
step like optimizer state, and `fp8_dot` casts operands to float8 with the
current scale, runs the dot (MXU-native on hardware with fp8 support; XLA
upcasts transparently elsewhere), then updates the history.

Recipe knobs mirror `FP8RecipeKwargs` (utils/dataclasses.py:137, ref
dataclasses.py:180): margin, amax_history_len, E4M3/HYBRID format.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..utils.dataclasses import FP8RecipeKwargs

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


class Fp8Meta(NamedTuple):
    """Delayed-scaling state for one tensor role (x / w / grad)."""

    scale: jax.Array         # multiplier applied before the fp8 cast
    amax_history: jax.Array  # [history_len] rolling raw-amax window

    @classmethod
    def init(cls, history_len: int = 16) -> "Fp8Meta":
        return cls(
            scale=jnp.ones((), jnp.float32),
            amax_history=jnp.zeros((history_len,), jnp.float32),
        )


def _fmt_max(fmt: str) -> float:
    return E4M3_MAX if fmt.upper() == "E4M3" else E5M2_MAX


def update_meta(meta: Fp8Meta, amax: jax.Array, fmt: str = "E4M3",
                margin: int = 0) -> Fp8Meta:
    """Roll the history and derive next step's scale (TE delayed scaling)."""
    history = jnp.roll(meta.amax_history, 1).at[0].set(amax)
    amax_max = jnp.max(history)
    scale = jnp.where(
        amax_max > 0.0,
        (_fmt_max(fmt) / (2.0 ** margin)) / amax_max,
        jnp.ones((), jnp.float32),
    )
    return Fp8Meta(scale=scale, amax_history=history)


def fp8_cast(x: jax.Array, meta: Fp8Meta, fmt: str = "E4M3") -> jax.Array:
    dtype = jnp.float8_e4m3fn if fmt.upper() == "E4M3" else jnp.float8_e5m2
    fmax = _fmt_max(fmt)
    scaled = jnp.clip(x.astype(jnp.float32) * meta.scale, -fmax, fmax)
    return scaled.astype(dtype)


def fp8_dot(
    x: jax.Array,
    w: jax.Array,
    x_meta: Fp8Meta,
    w_meta: Fp8Meta,
    out_dtype=jnp.bfloat16,
    fmt: str = "E4M3",
    margin: int = 0,
) -> tuple[jax.Array, Fp8Meta, Fp8Meta]:
    """x @ w in fp8 with per-tensor delayed scaling.

    Returns (out, new_x_meta, new_w_meta); thread the metas through the train
    step as you would optimizer state.
    """
    x8 = fp8_cast(x, x_meta, fmt)
    w8 = fp8_cast(w, w_meta, fmt)
    out = jnp.dot(x8, w8, preferred_element_type=jnp.float32)
    out = out / (x_meta.scale * w_meta.scale)
    x_meta = update_meta(x_meta, jnp.max(jnp.abs(x)), fmt, margin)
    w_meta = update_meta(w_meta, jnp.max(jnp.abs(w)), fmt, margin)
    return out.astype(out_dtype), x_meta, w_meta


def init_fp8_state(params, recipe: FP8RecipeKwargs | None = None):
    """One (x, w) meta pair per 2D+ weight leaf, matching the param pytree
    structure (the functional analogue of TE's per-module buffers)."""
    recipe = recipe or FP8RecipeKwargs()

    def _leaf(p):
        if hasattr(p, "ndim") and p.ndim >= 2:
            return {
                "x": Fp8Meta.init(recipe.amax_history_len),
                "w": Fp8Meta.init(recipe.amax_history_len),
            }
        return None

    return jax.tree_util.tree_map(_leaf, params)
