"""Native fp8 training path (transformer-engine replacement).

Replaces ref utils/transformer_engine.py (84 LoC `convert_model` swapping
nn.Linear for te.Linear). The torch/TE recipe — E4M3 forward / E5M2 backward,
per-tensor scales from a rolling amax history ("delayed scaling") — is kept,
but expressed functionally: `Fp8Meta` pytree state threads through the train
step like optimizer state, and `fp8_dot` casts operands to float8 with the
current scale, runs the dot (MXU-native on hardware with fp8 support; XLA
upcasts transparently elsewhere), then updates the history.

Recipe knobs mirror `FP8RecipeKwargs` (utils/dataclasses.py:137, ref
dataclasses.py:180): margin, amax_history_len, E4M3/HYBRID format.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..utils.dataclasses import FP8RecipeKwargs

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


class Fp8Meta(NamedTuple):
    """Delayed-scaling state for one tensor role (x / w / grad)."""

    scale: jax.Array         # multiplier applied before the fp8 cast
    amax_history: jax.Array  # [history_len] rolling raw-amax window

    @classmethod
    def init(cls, history_len: int = 16) -> "Fp8Meta":
        return cls(
            scale=jnp.ones((), jnp.float32),
            amax_history=jnp.zeros((history_len,), jnp.float32),
        )


def _fmt_max(fmt: str) -> float:
    return E4M3_MAX if fmt.upper() == "E4M3" else E5M2_MAX


def update_meta(meta: Fp8Meta, amax: jax.Array, fmt: str = "E4M3",
                margin: int = 0) -> Fp8Meta:
    """Roll the history and derive next step's scale (TE delayed scaling)."""
    history = jnp.roll(meta.amax_history, 1).at[0].set(amax)
    amax_max = jnp.max(history)
    scale = jnp.where(
        amax_max > 0.0,
        (_fmt_max(fmt) / (2.0 ** margin)) / amax_max,
        jnp.ones((), jnp.float32),
    )
    return Fp8Meta(scale=scale, amax_history=history)


def fp8_cast(x: jax.Array, meta: Fp8Meta, fmt: str = "E4M3") -> jax.Array:
    dtype = jnp.float8_e4m3fn if fmt.upper() == "E4M3" else jnp.float8_e5m2
    return _cast8(x, meta.scale, dtype, _fmt_max(fmt))


def fp8_dot(
    x: jax.Array,
    w: jax.Array,
    x_meta: Fp8Meta,
    w_meta: Fp8Meta,
    out_dtype=jnp.bfloat16,
    fmt: str = "E4M3",
    margin: int = 0,
) -> tuple[jax.Array, Fp8Meta, Fp8Meta]:
    """x @ w in fp8 with per-tensor delayed scaling.

    Returns (out, new_x_meta, new_w_meta); thread the metas through the train
    step as you would optimizer state.
    """
    x8 = fp8_cast(x, x_meta, fmt)
    w8 = fp8_cast(w, w_meta, fmt)
    out = jnp.dot(x8, w8, preferred_element_type=jnp.float32)
    out = out / (x_meta.scale * w_meta.scale)
    x_meta = update_meta(x_meta, jnp.max(jnp.abs(x)), fmt, margin)
    w_meta = update_meta(w_meta, jnp.max(jnp.abs(w)), fmt, margin)
    return out.astype(out_dtype), x_meta, w_meta


def _cast8(t: jax.Array, scale: jax.Array, dtype, fmax: float) -> jax.Array:
    return jnp.clip(t.astype(jnp.float32) * scale, -fmax, fmax).astype(dtype)


@jax.custom_vjp
def _fp8_matmul(x, w, x_scale, w_scale):
    """x[..., D] @ w[D, O]: E4M3 forward with the given delayed scales,
    E5M2 current-scaled backward (grad scale derived from the live grad
    inside the vjp, so no cross-step grad state is needed)."""
    return _fp8_matmul_fwd(x, w, x_scale, w_scale)[0]


def _fp8_matmul_fwd(x, w, x_scale, w_scale):
    x8 = _cast8(x, x_scale, jnp.float8_e4m3fn, E4M3_MAX)
    w8 = _cast8(w, w_scale, jnp.float8_e4m3fn, E4M3_MAX)
    out = jnp.dot(
        x8, w8, preferred_element_type=jnp.float32
    ) / (x_scale * w_scale)
    # zero-length dtype tokens ride the residuals so the backward can cast
    # cotangents to the PRIMALS' dtypes (f32 graphs must not silently get
    # bf16 weight grads)
    x_tok = jnp.zeros((0,), x.dtype)
    w_tok = jnp.zeros((0,), w.dtype)
    return out.astype(jnp.bfloat16), (x8, w8, x_scale, w_scale, x_tok, w_tok)


def _fp8_matmul_bwd(res, g):
    x8, w8, x_scale, w_scale, x_tok, w_tok = res
    amax_g = jnp.max(jnp.abs(g)).astype(jnp.float32)
    g_scale = jnp.where(amax_g > 0.0, E5M2_MAX / amax_g, 1.0)
    g8 = _cast8(g, g_scale, jnp.float8_e5m2, E5M2_MAX)
    dx = jnp.dot(
        g8, w8.T, preferred_element_type=jnp.float32
    ) / (g_scale * w_scale)
    g2 = g8.reshape(-1, g8.shape[-1])
    x2 = x8.reshape(-1, x8.shape[-1])
    dw = jnp.dot(
        x2.T, g2, preferred_element_type=jnp.float32
    ) / (x_scale * g_scale)
    return (
        dx.astype(x_tok.dtype),
        dw.astype(w_tok.dtype),
        jnp.zeros_like(x_scale),
        jnp.zeros_like(w_scale),
    )


_fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_dense(
    x: jax.Array,
    kernel: jax.Array,
    meta: dict,
    margin: int = 0,
) -> tuple[jax.Array, dict]:
    """The te.Linear replacement used inside models: x @ kernel with E4M3
    delayed scaling on both operands (ref utils/transformer_engine.py:24-84
    swaps nn.Linear for te.Linear; here the dense call itself swaps). Takes
    and returns {'x': Fp8Meta, 'w': Fp8Meta}; thread it through the train
    step like optimizer state. Backward runs E5M2 with current scaling."""
    out = _fp8_matmul(x, kernel, meta["x"].scale, meta["w"].scale)
    stop = jax.lax.stop_gradient
    new_meta = {
        "x": update_meta(meta["x"], stop(jnp.max(jnp.abs(x))).astype(jnp.float32), "E4M3", margin),
        "w": update_meta(meta["w"], stop(jnp.max(jnp.abs(kernel))).astype(jnp.float32), "E4M3", margin),
    }
    return out, new_meta


def fp8_expert_dense(
    x: jax.Array,
    kernel: jax.Array,
    meta: dict,
    margin: int = 0,
) -> tuple[jax.Array, dict]:
    """Per-expert batched fp8 projection: x [E, T, H] (or [T, H], shared
    across experts) @ kernel [E, H, F] -> [E, T, F]. ONE delayed-scale pair
    covers the stacked expert tensor (per-tensor scaling, the TE
    convention); the vmap batches the same custom-vjp fp8 matmul the dense
    path uses, so the backward is E5M2 current-scaled too."""
    in_axes = (0 if x.ndim == 3 else None, 0, None, None)
    out = jax.vmap(_fp8_matmul, in_axes=in_axes)(
        x, kernel, meta["x"].scale, meta["w"].scale)
    stop = jax.lax.stop_gradient
    new_meta = {
        "x": update_meta(meta["x"], stop(jnp.max(jnp.abs(x))).astype(jnp.float32), "E4M3", margin),
        "w": update_meta(meta["w"], stop(jnp.max(jnp.abs(kernel))).astype(jnp.float32), "E4M3", margin),
    }
    return out, new_meta


def fp8_state_history_len(fp8_state) -> int | None:
    """The amax-history window length of a delayed-scaling state tree (from
    its first `Fp8Meta` leaf), or None when the tree holds none."""
    for leaf in jax.tree_util.tree_leaves(
        fp8_state, is_leaf=lambda x: isinstance(x, Fp8Meta)
    ):
        if isinstance(leaf, Fp8Meta):
            return int(leaf.amax_history.shape[-1])
    return None


def adapt_history_len(fp8_state, history_len: int):
    """Resize every `Fp8Meta.amax_history` window (last dim) to
    ``history_len``: truncation keeps the NEWEST entries (index 0 is the
    most recent — `update_meta` rolls right), padding appends zeros (a zero
    amax is "no observation" and never wins the max). Scales pass through
    untouched, so the restored schedule continues exactly where it left off.

    Accepts abstract leaves (`jax.ShapeDtypeStruct`) too, so checkpoint
    restore can build a like-tree matching an on-disk window that differs
    from the live config — e.g. old checkpoints written under TE's 1024
    default restoring into today's 16-step window.
    """

    def _adapt(meta):
        if not isinstance(meta, Fp8Meta):
            return meta
        hist = meta.amax_history
        h = int(hist.shape[-1])
        if h == history_len:
            return meta
        if isinstance(hist, jax.ShapeDtypeStruct):
            shape = tuple(hist.shape[:-1]) + (history_len,)
            return Fp8Meta(
                scale=meta.scale,
                amax_history=jax.ShapeDtypeStruct(shape, hist.dtype),
            )
        if h > history_len:
            new = hist[..., :history_len]
        else:
            pad = [(0, 0)] * (hist.ndim - 1) + [(0, history_len - h)]
            new = jnp.pad(hist, pad)
        return Fp8Meta(scale=meta.scale, amax_history=new)

    return jax.tree_util.tree_map(
        _adapt, fp8_state, is_leaf=lambda x: isinstance(x, Fp8Meta)
    )


def resolve_history_len(explicit: int | None = None) -> int:
    """amax-history window: explicit arg > the live Accelerator's
    `FP8RecipeKwargs` kwargs-handler > the dataclass default (16 here — TE's
    1024-step window buys nothing under delayed scaling with per-step jit
    and costs [L, H] state per projection)."""
    if explicit is not None:
        return explicit
    from ..state import AcceleratorState

    if AcceleratorState._shared_state:
        recipe = AcceleratorState._shared_state.get("fp8_recipe_handler")
        if recipe is not None and recipe.amax_history_len is not None:
            return recipe.amax_history_len
    return 16


def stacked_fp8_metas(num_layers: int, groups: dict[str, tuple],
                      history_len: int | None = None) -> dict:
    """The model zoo's shared init_fp8_state body: per-layer delayed-scaling
    meta pairs for every projection name, stacked on the layer dim so they
    ride the forward's `lax.scan` (the functional analogue of
    transformer-engine's per-module buffers, ref
    utils/transformer_engine.py:24-84).

    `groups` maps module group -> projection names, e.g.
    ``{"attn": ("q_proj", ...), "mlp": ("gate_proj", ...)}``;
    `history_len` resolves via `resolve_history_len` (so
    ``Accelerator(kwargs_handlers=[FP8RecipeKwargs(amax_history_len=N)])``
    reaches every family without threading)."""
    h = resolve_history_len(history_len)

    def pair():
        # fresh arrays per role: shared buffers would be donated twice by
        # the fused train step
        return {
            "x": Fp8Meta(
                scale=jnp.ones((num_layers,), jnp.float32),
                amax_history=jnp.zeros((num_layers, h), jnp.float32),
            ),
            "w": Fp8Meta(
                scale=jnp.ones((num_layers,), jnp.float32),
                amax_history=jnp.zeros((num_layers, h), jnp.float32),
            ),
        }

    return {
        "layers": {
            group: {name: pair() for name in names}
            for group, names in groups.items()
        }
    }


def init_fp8_state(params, recipe: FP8RecipeKwargs | None = None):
    """One (x, w) meta pair per 2D+ weight leaf, matching the param pytree
    structure (the functional analogue of TE's per-module buffers)."""
    recipe = recipe or FP8RecipeKwargs()

    h = (
        recipe.amax_history_len
        if recipe.amax_history_len is not None
        else resolve_history_len()
    )

    def _leaf(p):
        if hasattr(p, "ndim") and p.ndim >= 2:
            return {
                "x": Fp8Meta.init(h),
                "w": Fp8Meta.init(h),
            }
        return None

    return jax.tree_util.tree_map(_leaf, params)
