"""Native int8/int4 weight-only quantization.

Replaces ref utils/bnb.py:44-467 (`load_and_quantize_model`,
`replace_with_bnb_layers`), which delegated to bitsandbytes CUDA kernels.
TPU-native version: symmetric block-wise quantization over the last axis,
stored as an int8 (or nibble-packed int4) pytree leaf + bf16 scales.
Dequantization happens inside the consuming jitted matmul — XLA fuses the
`q * scale` expansion into the dot's operand pipeline, so quantized weights
cost HBM, not extra FLOP passes.

`QuantizedTensor` is a registered pytree node, so quantized params flow
through `jax.jit` / sharding / checkpointing like any other leaf.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.dataclasses import QuantizationConfig


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Block-quantized weight: `data` int8 codes (+ nibble packing for int4),
    `scales` per (row..., block)."""

    def __init__(self, data, scales, bits: int, shape: tuple, dtype):
        self.data = data
        self.scales = scales
        self.bits = bits
        self.shape = tuple(shape)
        self.dtype = dtype

    def tree_flatten(self):
        return (self.data, self.scales), (self.bits, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scales = children
        bits, shape, dtype = aux
        return cls(data, scales, bits, shape, dtype)

    @property
    def nbytes(self) -> int:
        return self.data.size * self.data.dtype.itemsize + self.scales.nbytes

    def __repr__(self) -> str:
        return (
            f"QuantizedTensor(bits={self.bits}, shape={self.shape}, "
            f"dtype={self.dtype})"
        )


def _pack_int4(codes: jax.Array) -> jax.Array:
    """[-8,7] int8 codes -> two nibbles per byte along the last axis
    (odd widths get a zero nibble of padding; unpack slices it back off)."""
    u = (codes + 8).astype(jnp.uint8)  # [0,15]
    if u.shape[-1] % 2:
        pad = [(0, 0)] * (u.ndim - 1) + [(0, 1)]
        u = jnp.pad(u, pad)
    lo, hi = u[..., 0::2], u[..., 1::2]
    return (lo | (hi << 4)).astype(jnp.uint8)


def _unpack_int4(packed: jax.Array) -> jax.Array:
    lo = (packed & 0x0F).astype(jnp.int8) - 8
    hi = (packed >> 4).astype(jnp.int8) - 8
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(*packed.shape[:-1], packed.shape[-1] * 2)


def quantize(w, bits: int = 8, block_size: int = 128) -> QuantizedTensor:
    """Symmetric block-wise quantization over the last axis.

    jax.Array input stays on device (jit-compatible); numpy input (incl.
    np.memmap from an offload store) is quantized host-side with numpy math —
    no HBM is touched, so huge checkpoints quantize within host RAM.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    host = not isinstance(w, jax.Array)
    xp = np if host else jnp
    *lead, n = w.shape
    if n % block_size != 0:
        block_size = n  # degenerate: one block per row
    nb = n // block_size
    wf = xp.asarray(w, jnp.float32 if not host else np.float32).reshape(
        *lead, nb, block_size
    )
    absmax = xp.max(xp.abs(wf), axis=-1)
    qmax = 127.0 if bits == 8 else 7.0
    scales = (absmax / qmax).astype(jnp.bfloat16)
    safe = xp.maximum(absmax, 1e-12) / qmax
    codes = xp.clip(
        xp.round(wf / safe[..., None]), -qmax - 1, qmax
    ).astype(xp.int8).reshape(*lead, n)
    if bits == 4:
        codes = _pack_int4_np(codes) if host else _pack_int4(codes)
    return QuantizedTensor(codes, scales, bits, w.shape, w.dtype)


def _pack_int4_np(codes: np.ndarray) -> np.ndarray:
    u = (codes.astype(np.int16) + 8).astype(np.uint8)
    if u.shape[-1] % 2:
        pad = [(0, 0)] * (u.ndim - 1) + [(0, 1)]
        u = np.pad(u, pad)
    lo, hi = u[..., 0::2], u[..., 1::2]
    return (lo | (hi << 4)).astype(np.uint8)


def dequantize(qt: QuantizedTensor, dtype=None) -> jax.Array:
    codes = qt.data
    if qt.bits == 4:
        codes = _unpack_int4(codes)[..., : qt.shape[-1]]  # drop pad nibble
    *lead, n = qt.shape
    nb = qt.scales.shape[-1]
    wf = codes.astype(jnp.float32).reshape(*lead, nb, n // nb)
    wf = wf * qt.scales[..., None].astype(jnp.float32)
    return wf.reshape(*qt.shape).astype(dtype or qt.dtype)


def kv_quantize_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-row int8 quantization for KV-cache rows: x [..., D]
    -> (codes int8 [..., D], scales bf16 [...]). This is `quantize`'s
    block-scale rule with block_size == D — one scale per (row, head) —
    jit-friendly and shape-preserving so the serving cache can scatter
    codes and scales with the same indices it scatters bf16 rows with.
    Per-ROW scales (not per-page) keep appends independent: writing a new
    row into a partially-filled page never re-scales its neighbours, so
    shared (copy-on-write) pages stay bit-stable however many sharers
    race."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scales = (absmax / 127.0).astype(jnp.bfloat16)
    safe = jnp.maximum(absmax, 1e-12) / 127.0
    codes = jnp.clip(jnp.round(xf / safe[..., None]), -128, 127).astype(
        jnp.int8)
    return codes, scales


def kv_dequantize_rows(codes: jax.Array, scales: jax.Array,
                       dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of `kv_quantize_rows`: codes [..., D] * scales [...] ->
    [..., D] in `dtype` (f32 multiply, like `dequantize`)."""
    return (codes.astype(jnp.float32)
            * scales.astype(jnp.float32)[..., None]).astype(dtype)


def quantized_matmul(x: jax.Array, qt: QuantizedTensor) -> jax.Array:
    """x @ w with w quantized; dequant fuses into the dot under jit."""
    w = dequantize(qt, dtype=x.dtype)
    return x @ w


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def quantize_params(params: Any, config: QuantizationConfig | None = None) -> Any:
    """Walk a param pytree quantizing weight matrices (ndim >= 2); skips
    `config.skip_modules` substrings (ref bnb.py keeps lm_head fp16 for the
    same reason: output quality)."""
    config = config or QuantizationConfig(load_in_8bit=True)
    bits = config.bits
    if bits >= 16:
        return params

    def _maybe_quantize(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim < 2:
            return leaf
        name = _path_str(path)
        if any(skip in name for skip in config.skip_modules):
            return leaf
        return quantize(leaf, bits=bits, block_size=config.block_size)

    return jax.tree_util.tree_map_with_path(_maybe_quantize, params)


def dequantize_params(params: Any, dtype=None) -> Any:
    return jax.tree_util.tree_map(
        lambda leaf: dequantize(leaf, dtype=dtype)
        if isinstance(leaf, QuantizedTensor) else leaf,
        params,
        is_leaf=lambda leaf: isinstance(leaf, QuantizedTensor),
    )


def quantized_nbytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total
