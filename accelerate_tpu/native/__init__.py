"""Native (C++) runtime components, with pure-Python fallbacks.

The reference delegates its host-side input machinery to torch DataLoader
worker processes and torch-xla's MpDeviceLoader threads (ref
data_loader.py:518-559); this package owns that machinery natively:
`token_loader.cpp` memory-maps tokenized corpora and assembles shuffled,
host-sharded batches on producer threads behind a C ABI.

The shared library builds on demand with g++ (cached beside the source);
`TokenCorpusLoader` transparently falls back to a NumPy implementation with
IDENTICAL semantics (same permutation, sharding, wraparound) when no
toolchain is available, so behavior never depends on the build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native")
_SRC = os.path.join(_SRC_DIR, "token_loader.cpp")

_DTYPES = {np.dtype(np.uint16): 0, np.dtype(np.int32): 1, np.dtype(np.uint32): 2}

_lib = None
_lib_lock = threading.Lock()
_build_error: str | None = None


def _build_dir() -> str:
    override = os.environ.get("ACCELERATE_TPU_NATIVE_CACHE")
    candidates = [override] if override else [
        os.path.join(_SRC_DIR, "_build"),  # read-only installs fall through
        os.path.join(tempfile.gettempdir(), f"accelerate_tpu_native_{os.getuid()}"),
    ]
    for d in candidates:
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
            st = os.stat(d)
            # refuse dirs we don't own or that others can write: a planted
            # .so in a predictable shared path would be dlopened into the
            # training process
            if st.st_uid != os.getuid() or (st.st_mode & 0o022):
                continue
            if os.access(d, os.W_OK):
                return d
        except OSError:
            continue
    raise OSError(f"no safe writable native build dir among {candidates}")


def _load_library():
    """Compile (once) and dlopen the native library; None if unavailable."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            so_path = os.path.join(_build_dir(), "libatl.so")
            if not os.path.exists(so_path) or (
                os.path.getmtime(so_path) < os.path.getmtime(_SRC)
            ):
                # unique temp output + atomic rename: N launcher workers can
                # race this build without anyone dlopening a half-written .so
                tmp_out = f"{so_path}.{os.getpid()}.tmp"
                cmd = [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    "-pthread", _SRC, "-o", tmp_out,
                ]
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp_out, so_path)
            lib = ctypes.CDLL(so_path)
        except (OSError, subprocess.CalledProcessError, FileNotFoundError) as e:
            _build_error = getattr(e, "stderr", None) or str(e)
            return None
        lib.atl_open.restype = ctypes.c_void_p
        lib.atl_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_long]
        lib.atl_num_samples.restype = ctypes.c_long
        lib.atl_num_samples.argtypes = [ctypes.c_void_p]
        lib.atl_num_tokens.restype = ctypes.c_long
        lib.atl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.atl_close.argtypes = [ctypes.c_void_p]
        lib.atl_loader_new.restype = ctypes.c_void_p
        lib.atl_loader_new.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.atl_loader_batches_per_epoch.restype = ctypes.c_long
        lib.atl_loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
        lib.atl_loader_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.atl_loader_next.restype = ctypes.c_int
        lib.atl_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)
        ]
        lib.atl_loader_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_available() -> bool:
    """True if the native library is built (or buildable) on this host."""
    return _load_library() is not None


def build_error() -> str | None:
    _load_library()
    return _build_error


_MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)
# largest corpus (in samples) for which the fallback reproduces the native
# shuffle bit-for-bit (the swap loop is Python-sequential, ~1s per 2M)
_EXACT_SHUFFLE_MAX = int(os.environ.get("ACCELERATE_TPU_EXACT_SHUFFLE_MAX",
                                        2_000_000))


def _splitmix64_draws(seed: int, epoch: int, n: int) -> np.ndarray:
    """The SplitMix64 stream token_loader.cpp uses, vectorized: draw k is
    mix(seed_epoch + (k+1)*GAMMA)."""
    gamma = np.uint64(0x9E3779B97F4A7C15)
    s0 = np.uint64((seed ^ (epoch * 0xD1B54A32D192ED03)) & 0xFFFFFFFFFFFFFFFF)
    with np.errstate(over="ignore"):
        z = (s0 + (np.arange(1, n + 1, dtype=np.uint64)) * gamma) & _MASK64
        z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _MASK64
        z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _MASK64
        return z ^ (z >> np.uint64(31))


def _epoch_order(num_samples: int, seed: int, epoch: int, shuffle: bool,
                 rank: int, world: int) -> np.ndarray:
    """The EXACT permutation+shard the C++ side computes (SplitMix64
    Fisher-Yates, wraparound stride shard): mixed native/fallback fleets
    therefore see bit-identical epoch orders and disjoint host shards."""
    idx = np.arange(num_samples, dtype=np.int64)
    if shuffle and num_samples > 1:
        if num_samples > _EXACT_SHUFFLE_MAX:
            # the bit-exact Fisher-Yates swap loop is Python-sequential;
            # above this size use numpy's C shuffle instead. Still
            # deterministic per (seed, epoch) — but a fleet MIXING native
            # and fallback hosts would see different permutations, so warn.
            import warnings

            warnings.warn(
                f"corpus has {num_samples} samples; fallback shuffle switches "
                "to numpy (not bit-identical to the native loader). Ensure "
                "all hosts use the same implementation, or set "
                "ACCELERATE_TPU_EXACT_SHUFFLE_MAX higher.",
                stacklevel=2,
            )
            rng = np.random.default_rng((seed ^ (epoch * 0xD1B54A32D192ED03)) & 0xFFFFFFFF)
            rng.shuffle(idx)
        else:
            draws = _splitmix64_draws(seed, epoch, num_samples - 1)
            for k, i in enumerate(range(num_samples - 1, 0, -1)):
                j = int(draws[k] % np.uint64(i + 1))
                idx[i], idx[j] = idx[j], idx[i]
    per = -(-num_samples // world)
    take = (rank + np.arange(per, dtype=np.int64) * world) % num_samples
    return idx[take]


class TokenCorpusLoader:
    """Iterate `{"input_ids": int32 [batch, sample_len]}` batches from a flat
    binary token file.

    Sized batch iterable — plugs straight into `Accelerator.prepare`/
    `prepare_data_loader`. Construct with `rank=state.process_index,
    world=state.num_processes`: the loader shards the corpus itself and sets
    `is_host_sharded`, which tells `prepare_data_loader` NOT to stride its
    batches across hosts a second time.

    Uses the C++ core when available, else the NumPy fallback.
    """

    def __init__(
        self,
        path: str,
        sample_len: int,
        batch_size: int,
        dtype: np.dtype | str = np.int32,
        shuffle: bool = True,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        drop_last: bool = True,
        threads: int = 2,
        prefetch_depth: int = 4,
        force_fallback: bool = False,
    ) -> None:
        self.path = path
        self.sample_len = int(sample_len)
        self.batch_size = int(batch_size)
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPES:
            raise ValueError(f"dtype {self.dtype} not supported; use uint16/int32/uint32")
        self.shuffle = shuffle
        self.seed = int(seed)
        self.rank, self.world = int(rank), int(world)
        if self.world <= 0 or not (0 <= self.rank < self.world):
            raise ValueError(f"invalid shard rank={rank} world={world}")
        if self.batch_size <= 0 or self.sample_len <= 0:
            raise ValueError(
                f"batch_size/sample_len must be positive, got "
                f"{batch_size}/{sample_len}"
            )
        # downstream prepare() must not shard again: this loader already
        # yields only this host's shard
        self.is_host_sharded = self.world > 1
        self.drop_last = drop_last
        self.threads, self.prefetch_depth = threads, prefetch_depth
        self.epoch = 0

        self._lib = None if force_fallback else _load_library()
        self._corpus = None
        self._loader = None
        if self._lib is not None:
            self._corpus = self._lib.atl_open(
                path.encode(), _DTYPES[self.dtype], self.sample_len
            )
            if not self._corpus:
                raise FileNotFoundError(f"cannot mmap token file {path}")
            self.num_samples = self._lib.atl_num_samples(self._corpus)
            self._loader = self._lib.atl_loader_new(
                self._corpus, self.batch_size, int(shuffle), self.seed,
                self.rank, self.world, int(drop_last), threads, prefetch_depth,
            )
            if not self._loader:
                raise RuntimeError(
                    "native loader creation failed (args rejected by atl_loader_new)"
                )
        else:
            self._mm = np.memmap(path, dtype=self.dtype, mode="r")
            self.num_samples = len(self._mm) // self.sample_len
        per = -(-self.num_samples // self.world)
        self.num_batches = (
            per // self.batch_size if drop_last
            else -(-per // self.batch_size)
        )
        # drop_last=False wraps the final batch with recycled rows; report
        # them like every other loader so gather_for_metrics can drop them
        # (DataLoaderShard reads these at end of epoch). Only exact when the
        # host shards themselves are even (num_samples % world == 0) — with
        # uneven shards the wrapped rows are cross-host duplicates that the
        # uniform (hosts, batch, real) layout cannot identify.
        real_tail = per - self.batch_size * (self.num_batches - 1)
        if (not drop_last and 0 < real_tail < self.batch_size
                and self.num_samples % self.world == 0):
            self.remainder = real_tail * self.world
            self.tail_layout = (self.world, self.batch_size, real_tail)
        else:
            self.remainder = -1
            self.tail_layout = None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self):
        if self._loader is not None:
            yield from self._iter_native()
        else:
            yield from self._iter_fallback()
        self.epoch += 1

    def _iter_native(self):
        out = np.empty((self.batch_size, self.sample_len), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        self._lib.atl_loader_start_epoch(self._loader, self.epoch)
        while True:
            rc = self._lib.atl_loader_next(self._loader, ptr)
            if rc != 0:
                break
            yield {"input_ids": out.copy()}

    def _iter_fallback(self):
        order = _epoch_order(
            self.num_samples, self.seed, self.epoch, self.shuffle,
            self.rank, self.world,
        )
        L, B = self.sample_len, self.batch_size
        tokens = self._mm
        n = len(order)
        for b in range(self.num_batches):
            rows = [order[(b * B + i) % n] for i in range(B)]
            batch = np.stack(
                [np.asarray(tokens[r * L : (r + 1) * L], dtype=np.int32) for r in rows]
            )
            yield {"input_ids": batch}

    def close(self) -> None:
        if self._loader is not None:
            self._lib.atl_loader_free(self._loader)
            self._loader = None
        if self._corpus is not None:
            self._lib.atl_close(self._corpus)
            self._corpus = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def write_token_file(path: str, tokens: np.ndarray) -> str:
    """Write a flat binary token file in a supported dtype."""
    arr = np.ascontiguousarray(tokens)
    if arr.dtype not in _DTYPES:
        arr = arr.astype(np.int32)
    arr.tofile(path)
    return path
