"""Experiment trackers.

TPU-native analogue of ref src/accelerate/tracking.py (1023 LoC): a
`GeneralTracker` ABC with `@on_main_process`-gated methods and concrete
backends gated on availability (ref :91-163, selection `filter_trackers`
:971). The reference ships 8 backends (TensorBoard/WandB/Comet/Aim/MLflow/
ClearML/DVCLive); here the always-available native backend is `JSONLTracker`
(dependency-free, one JSON line per log call), with TensorBoard/WandB/MLflow/
Comet/Aim/ClearML wired when their packages exist.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Any

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def _scalarize(v):
    """Coerce 0-d jax/numpy values to Python scalars so the isinstance
    filters below accept the metrics a JAX loop actually produces."""
    if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
        return v.item()
    return v


def _scalarize_all(values: dict) -> dict:
    """Batch-scalarize a metrics dict: ALL device-resident 0-d values cross
    to the host in ONE `jax.device_get` transfer instead of one blocking
    `.item()` round trip per metric (N syncs per `log()` call was the
    telemetry hot-path host-sync the self-lint flagged). Host values pass
    through `_scalarize` unchanged."""
    device = {
        k: v for k, v in values.items()
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0
        and hasattr(v, "is_fully_replicated")  # jax.Array, not numpy
    }
    if device:
        import jax

        values = {**values, **jax.device_get(device)}
    return {k: _scalarize(v) for k, v in values.items()}


def on_main_process(function):
    """ref tracking.py:67-84."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """ref tracking.py:91. Subclass with `name`, `requires_logging_directory`,
    and implement `store_init_configuration` / `log`."""

    name: str = "generic"
    requires_logging_directory: bool = False
    main_process_only: bool = True

    def __init__(self, run_name: str | None = None, **kwargs: Any):
        self.run_name = run_name

    @property
    def tracker(self):
        return None

    def store_init_configuration(self, values: dict) -> None:
        raise NotImplementedError

    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        raise NotImplementedError

    def log_images(self, values: dict, step: int | None = None, **kwargs) -> None:
        pass

    def finish(self) -> None:
        pass


class JSONLTracker(GeneralTracker):
    """Native dependency-free tracker: one JSON object per line. No reference
    equivalent — our always-on default so `log_with="all"` works hermetically."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__(run_name)
        logging_dir = logging_dir or "."
        os.makedirs(os.path.join(logging_dir, run_name), exist_ok=True)
        self.path = os.path.join(logging_dir, run_name, "metrics.jsonl")
        self._fh = open(self.path, "a")

    @property
    def tracker(self):
        return self._fh

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._write({"event": "config", "config": _jsonable(values)})

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        # batch the top-level device scalars into one transfer; _jsonable
        # still catches stragglers in nested containers
        self._write({"event": "log", "step": step, "ts": time.time(),
                     **_jsonable(_scalarize_all(values))})

    def _write(self, obj: dict) -> None:
        self._fh.write(json.dumps(obj) + "\n")
        self._fh.flush()

    @on_main_process
    def finish(self) -> None:
        self._fh.close()


class TensorBoardTracker(GeneralTracker):
    """ref tracking.py:165."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__(run_name)
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard

        self.logging_dir = os.path.join(logging_dir or ".", run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        for k, v in _scalarize_all(values).items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class WandBTracker(GeneralTracker):
    """ref tracking.py:276."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """ref tracking.py:579."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__(run_name)
        import mlflow

        mlflow.set_experiment(run_name)
        self.run = mlflow.start_run(**kwargs)
        self._mlflow = mlflow

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        for k, v in _flatten_scalars(values).items():
            self._mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        metrics = {
            k: v for k, v in _scalarize_all(values).items()
            if isinstance(v, (int, float))
        }
        self._mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self) -> None:
        self._mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """ref tracking.py:399."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        from comet_ml import Experiment

        self.writer = Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        if step is not None:
            self.writer.set_step(step)
        self.writer.log_metrics(values, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.end()


class AimTracker(GeneralTracker):
    """ref tracking.py:480."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__(run_name)
        from aim import Run

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """ref tracking.py:724."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        from clearml import Task

        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        logger_obj = self.task.get_logger()
        for k, v in _scalarize_all(values).items():
            if isinstance(v, (int, float)):
                logger_obj.report_scalar(title=k, series=k, value=v, iteration=step or 0)

    @on_main_process
    def finish(self) -> None:
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    """ref tracking.py:876."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, live=None, **kwargs):
        super().__init__(run_name)
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs) -> None:
        if step is not None:
            self.live.step = step
        for k, v in _scalarize_all(values).items():
            if isinstance(v, (int, float)):
                self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self) -> None:
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
}


def filter_trackers(
    log_with: list,
    logging_dir: str | None = None,
    run_name: str = "accelerate_tpu",
    init_kwargs: dict | None = None,
) -> list[GeneralTracker]:
    """ref tracking.py:971. Resolves names/'all'/instances into live trackers,
    skipping unavailable backends with a warning."""
    init_kwargs = init_kwargs or {}
    names: list = []
    for entry in log_with or []:
        if isinstance(entry, GeneralTracker):
            names.append(entry)
        else:
            value = str(LoggerType(str(entry).lower()) if not isinstance(entry, LoggerType) else entry)
            if value == "all":
                names.extend(n for n in LOGGER_TYPE_TO_CLASS if _AVAILABILITY[n]())
            else:
                names.append(value)
    trackers: list[GeneralTracker] = []
    seen = set()
    for entry in names:
        if isinstance(entry, GeneralTracker):
            trackers.append(entry)
            continue
        if entry in seen:
            continue
        seen.add(entry)
        cls = LOGGER_TYPE_TO_CLASS.get(entry)
        if cls is None or not _AVAILABILITY[entry]():
            logger.warning(f"Tracker {entry} unavailable; skipping")
            continue
        kwargs = dict(init_kwargs.get(entry, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir)
        trackers.append(cls(run_name, **kwargs))
    return trackers


def _jsonable(tree: Any) -> Any:
    if isinstance(tree, dict):
        return {k: _jsonable(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_jsonable(v) for v in tree]
    if hasattr(tree, "item") and getattr(tree, "ndim", 1) == 0:
        return tree.item()
    if isinstance(tree, (int, float, str, bool, type(None))):
        return tree
    return str(tree)


def _flatten_scalars(values: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in values.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten_scalars(v, key))
        elif isinstance(v, (int, float, str, bool)):
            out[key] = v
        else:
            out[key] = str(v)
    return out
