"""Optimizer wrapper.

TPU-native analogue of ref src/accelerate/optimizer.py (214 LoC). The
reference wraps a stateful torch optimizer and *skips* `step()` during
gradient accumulation (ref optimizer.py:153), runs the AMP scaler's
overflow-skip logic (:155-168), and on XLA all-reduces fetched grads
(:140-146). Here the optimizer is an optax `GradientTransformation` — pure
functions over pytrees — and gradients arrive already globally averaged
(GSPMD inserts the reductions), so what remains is:

- owning the (sharded) `opt_state` and the accumulation buffer,
- the accumulate-then-apply step gate,
- fp16 overflow skipping (`is_overflow`, ref optimizer.py:192),
- device placement of loaded state (ref :28-35).

`AcceleratedOptimizer` is the *eager-parity* facade for reference-style
loops; the fused `Accelerator.train_step` path folds the same update into
one compiled program and does not use this class's Python-side gate.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from .state import AcceleratorState, GradientState


@partial(jax.jit, donate_argnums=(0,))
def _accumulate(buffer, grads, scale):
    return jax.tree_util.tree_map(lambda b, g: b + g * scale, buffer, grads)


def _zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


class AcceleratedOptimizer:
    """Stateful facade over an optax transformation.

    Usage (reference-style loop):
        optimizer = accelerator.prepare(optax.adamw(1e-3), params=params)
        ...
        loss, grads = accelerator.compute_gradients(loss_fn, optimizer.params, batch)
        accelerator.backward(grads)        # accumulates
        optimizer.step()                   # no-op unless sync boundary
        optimizer.zero_grad()
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        params: Any = None,
        opt_state: Any = None,
        param_sharding: Any = None,
        opt_sharding: Any = None,
    ):
        self.tx = tx
        self.gradient_state = GradientState()
        self.params = params
        self.param_sharding = param_sharding
        self.opt_sharding = opt_sharding
        if opt_state is None and params is not None:
            opt_state = tx.init(params)
            if opt_sharding is not None:
                opt_state = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(x, s), opt_state, opt_sharding
                )
        self.opt_state = opt_state
        self._grad_buffer = None
        self._accum_count = 0
        self._overflow = False
        self._apply = None  # jitted (params, opt_state, grads) -> (params, opt_state)

    # -- gradient buffer (torch `.grad` analogue) ---------------------------
    def accumulate_grads(self, grads: Any, scale: float = 1.0) -> None:
        if self._grad_buffer is None:
            self._grad_buffer = _zeros_like(grads)
        self._grad_buffer = _accumulate(self._grad_buffer, grads, scale)
        self._accum_count += 1

    @property
    def gradients(self) -> Any:
        return self._grad_buffer

    @gradients.setter
    def gradients(self, value: Any) -> None:
        self._grad_buffer = value

    def zero_grad(self, set_to_none: bool = True) -> None:
        """ref optimizer.py:119 — drop the accumulation buffer."""
        self._grad_buffer = None
        self._accum_count = 0

    # -- step ----------------------------------------------------------------
    def _build_apply(self):
        @jax.jit
        def apply(params, opt_state, grads):
            updates, new_opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt_state

        return apply

    def step(self, grads: Any = None) -> None:
        """Apply the update unless we're mid-accumulation
        (ref optimizer.py:136-168)."""
        if not self.gradient_state.sync_gradients:
            return  # accumulating: skip, like DDP no_sync (ref :153)
        if grads is None:
            grads = self._grad_buffer
        if grads is None:
            raise ValueError(
                "No gradients: call accelerator.backward(grads) first or pass "
                "grads to step()."
            )
        if self._check_overflow(grads):
            self._overflow = True
            return  # fp16 scaler overflow: skip step (ref :155-168)
        self._overflow = False
        if self._apply is None:
            self._apply = self._build_apply()
        self.params, self.opt_state = self._apply(self.params, self.opt_state, grads)

    def _check_overflow(self, grads) -> bool:
        state = AcceleratorState() if AcceleratorState._shared_state else None
        if state is None or state.mixed_precision != "fp16":
            return False
        norm = optax.global_norm(grads)
        return not bool(jnp.isfinite(norm))

    @property
    def step_was_skipped(self) -> bool:
        """ref optimizer.py:192 `is_overflow`/`step_was_skipped`."""
        return self._overflow

    # -- state_dict parity ---------------------------------------------------
    def state_dict(self) -> dict:
        return {"opt_state": self.opt_state}

    def load_state_dict(self, state_dict: dict) -> None:
        opt_state = state_dict["opt_state"]
        if self.opt_sharding is not None:
            opt_state = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), opt_state, self.opt_sharding
            )
        # keep pytree structure of the existing state (loaded dicts may be raw)
        if self.opt_state is not None:
            flat = jax.tree_util.tree_leaves(opt_state)
            treedef = jax.tree_util.tree_structure(self.opt_state)
            self.opt_state = jax.tree_util.tree_unflatten(treedef, flat)
        else:
            self.opt_state = opt_state
