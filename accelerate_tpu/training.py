"""TrainState: the unit of training the fused path operates on.

The reference's `prepare()` returns wrapped *objects* (DDP module, optimizer,
scheduler) that coordinate eagerly per step (SURVEY.md §3.3). TPU-natively the
unit is one pytree carrying (params, opt_state, step, accumulation buffer,
loss scale) so the whole update — forward, backward, accumulate, clip,
optimizer, schedule — compiles into a single donated XLA program.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DynamicLossScale:
    """fp16 dynamic loss scaling — replaces torch.cuda.amp.GradScaler
    (ref accelerator.py:455-479). bf16 never needs it; kept for fp16 parity."""

    scale: jax.Array
    growth_tracker: jax.Array
    growth_interval: int = dataclasses.field(default=2000, metadata={"static": True})
    growth_factor: float = dataclasses.field(default=2.0, metadata={"static": True})
    backoff_factor: float = dataclasses.field(default=0.5, metadata={"static": True})

    @classmethod
    def create(cls, init_scale: float = 2.0**16) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_tracker=jnp.asarray(0, jnp.int32),
        )

    def update(self, grads_finite: jax.Array) -> "DynamicLossScale":
        tracker = jnp.where(grads_finite, self.growth_tracker + 1, 0)
        grow = tracker >= self.growth_interval
        scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.scale * self.growth_factor, self.scale),
            self.scale * self.backoff_factor,
        )
        return dataclasses.replace(
            self, scale=scale, growth_tracker=jnp.where(grow, 0, tracker)
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Params + optimizer state + accumulation state as one pytree.

    `apply_fn`/`tx` are static (not traced). `grad_accum` exists only when
    gradient accumulation is driven per-micro-batch (the eager-compatible
    fused step); the scan-fused step needs no buffer.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    grad_accum: Any
    loss_scale: DynamicLossScale | None
    apply_fn: Callable = dataclasses.field(metadata={"static": True})
    tx: optax.GradientTransformation = dataclasses.field(metadata={"static": True})
    # fp8 delayed-scaling metas (ops/fp8.py), threaded through the fused
    # step like optimizer state when mixed_precision="fp8"
    fp8_state: Any = None

    @classmethod
    def create(
        cls,
        *,
        apply_fn: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        use_grad_accum_buffer: bool = False,
        use_loss_scale: bool = False,
        fp8_state: Any = None,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            grad_accum=(
                jax.tree_util.tree_map(jnp.zeros_like, params)
                if use_grad_accum_buffer
                else None
            ),
            loss_scale=DynamicLossScale.create() if use_loss_scale else None,
            apply_fn=apply_fn,
            tx=tx,
            fp8_state=fp8_state,
        )

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return dataclasses.replace(
            self,
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


# ---------------------------------------------------------------------------
# goodput-grade resilient training loop (ISSUE 20)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResilienceReport:
    """What `run_resilient` lived through and what it cost."""

    state: Any                      # the final TrainState
    steps_completed: int            # global step index reached
    start_step: int                 # where this invocation (re)started
    resumes: int                    # in-process crash recoveries
    saves: int                      # checkpoints written
    preempted: bool                 # True when a drain signal ended the run
    goodput: float                  # StepTimer.goodput over the run
    taxonomy: dict                  # StepTimer.stall_taxonomy()
    checkpoint_dir: str
    last_commit_dir: str | None     # newest complete checkpoint at exit
    incidents: list                 # straggler reports raised during the run


def run_resilient(
    accelerator,
    state: "TrainState",
    step_fn: Callable,
    batch_fn: Callable,
    num_steps: int,
    checkpoint_dir: str,
    *,
    save_every: int = 0,
    keep_last_n: int = 2,
    timer: Any = None,
    max_resumes: int = 3,
    blocking_saves: bool = False,
    install_signal_handlers: bool = True,
    drain_signals: tuple = (signal.SIGTERM,),
    straggler_monitor: Any = None,
    poll_every: int = 0,
    restart_on_straggler: bool = False,
    on_step: Callable | None = None,
) -> ResilienceReport:
    """Preemption-tolerant training loop: step-overlapped checkpoints,
    SIGTERM drain-then-save, step-crash auto-resume from the last
    committed manifest, and the straggler closed loop — the goodput
    number stays honest because every save/stall is marked on `timer`.

    - `step_fn(state, batch) -> (state, metrics)` — the compiled step.
      Recompiles after an in-process resume are NOT paid (the jit cache
      survives); across a relaunch the persistent XLA compilation cache
      (`utils.environment.configure_compilation_cache`) pays them once.
    - `batch_fn(step_index) -> batch` must be deterministic in the step
      index — that is what makes the data position resumable (the host
      RNG streams restore too, for stochastic pipelines keyed on them).
    - `save_every > 0` checkpoints every N steps into
      `checkpoint_dir/step_<N>`, async by default (the device->host
      snapshot is the only in-loop cost; the write overlaps later
      steps), committed via manifest, pruned to `keep_last_n` (the
      newest complete commit is never deleted). `blocking_saves=True`
      is the measurement baseline: the full write blocks in-loop.
    - A drain signal (SIGTERM by default — the preemption notice) ends
      the loop at the next step boundary AFTER saving; crashes inside a
      step restore from the newest complete manifest and continue, at
      most `max_resumes` times, each leaving an incident bundle.
    - `straggler_monitor` (telemetry.StragglerMonitor) is polled every
      `poll_every` steps; `restart_on_straggler=True` wires its incident
      to the drain path — the single-job form of elastic restart.

    Returns a :class:`ResilienceReport`; `state` inside it is the final
    train state (also assigned through in place via the checkpoint
    restore on resume)."""
    from .checkpointing import latest_complete_checkpoint, prune_checkpoints
    from .profiler import StepTimer

    if timer is None:
        timer = StepTimer(warmup_steps=1, name="resilient_step")
    checkpoint_dir = os.path.abspath(os.path.expanduser(checkpoint_dir))
    os.makedirs(checkpoint_dir, exist_ok=True)

    resumed = accelerator.resume_latest(checkpoint_dir, state=state)
    start = int(resumed["step"]) if resumed is not None else 0
    last_commit = resumed["checkpoint_dir"] if resumed is not None else None

    drain = {"requested": False, "signum": None}

    def _request_drain(signum=None, frame=None):
        drain["requested"] = True
        drain["signum"] = signum

    if straggler_monitor is not None and restart_on_straggler \
            and straggler_monitor.on_straggler is None:
        straggler_monitor.on_straggler = lambda report: _request_drain()
    if straggler_monitor is not None and straggler_monitor.timer is None:
        straggler_monitor.timer = timer

    prev_handlers: dict = {}
    if install_signal_handlers \
            and threading.current_thread() is threading.main_thread():
        for sig in drain_signals:
            prev_handlers[sig] = signal.signal(sig, _request_drain)

    def _save(step_index: int, marked: bool) -> str:
        # accelerator.step is what save_accelerator_state persists as the
        # resume point — pin it to the loop's global step index
        accelerator.step = step_index
        target = os.path.join(checkpoint_dir, f"step_{step_index:08d}")
        if marked:
            kind = "checkpoint" if blocking_saves else "checkpoint_stage"
            with timer.overhead(kind):
                accelerator.save_state(target, state=state,
                                       async_save=not blocking_saves)
        else:
            accelerator.save_state(target, state=state,
                                   async_save=not blocking_saves)
        prune_checkpoints(checkpoint_dir, keep_last_n)
        return target

    resumes = saves = 0
    preempted = False
    incidents: list = []
    i = start
    try:
        while i < num_steps:
            if drain["requested"]:
                # drain-then-save: commit a resume point, then hand the
                # machine back — the relaunch continues from here
                _save(i, marked=False)
                accelerator.wait_for_checkpoints()
                saves += 1
                preempted = True
                break
            try:
                with timer.input_stall():
                    batch = batch_fn(i)
                with timer.dispatch():
                    state, metrics = step_fn(state, batch)
                timer.tick(state)
                if on_step is not None:
                    on_step(i, state, metrics)
            except Exception as exc:
                resumes += 1
                if resumes > max_resumes:
                    raise
                _write_crash_bundle(exc, accelerator)
                try:
                    # drain in-flight async saves so everything already
                    # enqueued publishes its manifest before we look for
                    # the newest complete commit
                    accelerator.wait_for_checkpoints()
                except Exception:
                    pass  # writer failure: sealed manifests were dropped
                restored = accelerator.resume_latest(checkpoint_dir,
                                                     state=state)
                if restored is None:
                    raise       # nothing committed yet: nothing to resume
                last_commit = restored["checkpoint_dir"]
                i = int(restored.get("step", 0))
                continue
            i += 1
            if save_every and i % save_every == 0 and i < num_steps:
                _save(i, marked=True)
                saves += 1
            if straggler_monitor is not None and poll_every \
                    and i % poll_every == 0:
                report = straggler_monitor.poll()
                if report is not None:
                    incidents.append(report)
        if not preempted and save_every and i > start:
            # final commit: un-marked on the timer — the goodput window
            # closed at the last tick, so marking post-window work would
            # subtract it without its wall time
            _save(i, marked=False)
            saves += 1
        accelerator.wait_for_checkpoints()
        if saves:
            # the periodic prunes ran before the async manifests published
            # (a not-yet-committed save is invisible to retention), so one
            # post-drain prune brings the directory down to keep_last_n
            prune_checkpoints(checkpoint_dir, keep_last_n=keep_last_n)
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)

    if saves:
        last_commit = latest_complete_checkpoint(checkpoint_dir) or last_commit
    goodput = timer.goodput
    return ResilienceReport(
        state=state,
        steps_completed=i,
        start_step=start,
        resumes=resumes,
        saves=saves,
        preempted=preempted,
        goodput=goodput if goodput == goodput else 0.0,
        taxonomy=timer.stall_taxonomy(),
        checkpoint_dir=checkpoint_dir,
        last_commit_dir=last_commit,
        incidents=incidents,
    )


def _write_crash_bundle(exc: BaseException, accelerator) -> str | None:
    """Best-effort incident bundle for a step-time crash (same location
    and format as the stall watchdog's)."""
    try:
        from .telemetry.watchdog import (build_exception_report,
                                         resolve_incident_dir,
                                         write_incident_bundle)

        base = resolve_incident_dir(None)
        if base is None:
            return None
        report = build_exception_report(exc, name="step-crash")
        report["kind"] = "step_crash"
        return write_incident_bundle(
            base, report, registry=getattr(accelerator, "telemetry", None),
            name="step-crash")
    except Exception:
        return None


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves (the bf16 compute policy: fp32 master params cast
    at trace time — replaces torch autocast, ref accelerator.py:1356-1365)."""
    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def global_norm(tree: Any) -> jax.Array:
    return optax.global_norm(tree)


def clip_by_global_norm(tree: Any, max_norm: float) -> tuple[Any, jax.Array]:
    """Returns (clipped, pre-clip norm) — matches torch
    clip_grad_norm_'s return (ref accelerator.py:2221)."""
    norm = optax.global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * factor, tree), norm
