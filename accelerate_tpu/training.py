"""TrainState: the unit of training the fused path operates on.

The reference's `prepare()` returns wrapped *objects* (DDP module, optimizer,
scheduler) that coordinate eagerly per step (SURVEY.md §3.3). TPU-natively the
unit is one pytree carrying (params, opt_state, step, accumulation buffer,
loss scale) so the whole update — forward, backward, accumulate, clip,
optimizer, schedule — compiles into a single donated XLA program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DynamicLossScale:
    """fp16 dynamic loss scaling — replaces torch.cuda.amp.GradScaler
    (ref accelerator.py:455-479). bf16 never needs it; kept for fp16 parity."""

    scale: jax.Array
    growth_tracker: jax.Array
    growth_interval: int = dataclasses.field(default=2000, metadata={"static": True})
    growth_factor: float = dataclasses.field(default=2.0, metadata={"static": True})
    backoff_factor: float = dataclasses.field(default=0.5, metadata={"static": True})

    @classmethod
    def create(cls, init_scale: float = 2.0**16) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_tracker=jnp.asarray(0, jnp.int32),
        )

    def update(self, grads_finite: jax.Array) -> "DynamicLossScale":
        tracker = jnp.where(grads_finite, self.growth_tracker + 1, 0)
        grow = tracker >= self.growth_interval
        scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.scale * self.growth_factor, self.scale),
            self.scale * self.backoff_factor,
        )
        return dataclasses.replace(
            self, scale=scale, growth_tracker=jnp.where(grow, 0, tracker)
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Params + optimizer state + accumulation state as one pytree.

    `apply_fn`/`tx` are static (not traced). `grad_accum` exists only when
    gradient accumulation is driven per-micro-batch (the eager-compatible
    fused step); the scan-fused step needs no buffer.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    grad_accum: Any
    loss_scale: DynamicLossScale | None
    apply_fn: Callable = dataclasses.field(metadata={"static": True})
    tx: optax.GradientTransformation = dataclasses.field(metadata={"static": True})
    # fp8 delayed-scaling metas (ops/fp8.py), threaded through the fused
    # step like optimizer state when mixed_precision="fp8"
    fp8_state: Any = None

    @classmethod
    def create(
        cls,
        *,
        apply_fn: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        use_grad_accum_buffer: bool = False,
        use_loss_scale: bool = False,
        fp8_state: Any = None,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            grad_accum=(
                jax.tree_util.tree_map(jnp.zeros_like, params)
                if use_grad_accum_buffer
                else None
            ),
            loss_scale=DynamicLossScale.create() if use_loss_scale else None,
            apply_fn=apply_fn,
            tx=tx,
            fp8_state=fp8_state,
        )

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return dataclasses.replace(
            self,
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves (the bf16 compute policy: fp32 master params cast
    at trace time — replaces torch autocast, ref accelerator.py:1356-1365)."""
    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def global_norm(tree: Any) -> jax.Array:
    return optax.global_norm(tree)


def clip_by_global_norm(tree: Any, max_norm: float) -> tuple[Any, jax.Array]:
    """Returns (clipped, pre-clip norm) — matches torch
    clip_grad_norm_'s return (ref accelerator.py:2221)."""
    norm = optax.global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * factor, tree), norm
