"""Pod layer 2 (MPMD): disaggregated prefill/decode workers + the router.

Prefill and decode are different programs with different economics:
prefill is compute-bound (one long matmul burst per prompt, then done),
decode is latency/HBM-bound (one small step per token, forever). Sharing
one engine means every arriving prompt steals a step from every running
stream — the chunked-prefill interleave bounds the theft at one chunk,
but it never removes it. Splitting the roles does (the MPMD argument of
arxiv 2412.14374): dedicated PREFILL workers turn prompts into KV pages
and a first token, dedicated DECODE workers own slots and stream tokens,
and the pages ship between them (serving/pod/transfer.py — the hand-off
PR 5's paged cache made possible).

`PodRouter` is the host-side control plane gluing the roles together
behind the ordinary `ServingEngine` API (submit/stream/astream/cancel/
finish/step/run_until_idle, scheduler introspection, metrics,
debug views), so the HTTP front door, tenant tiers, SLO shedding, and
request tracing from the server layer run unchanged on top:

- admission: a zero-slot `Scheduler` subclass keeps the full tenant/
  tier/DRR/SLO policy surface as THE front queue; the router drains it
  in policy order onto the least-loaded prefill worker;
- page-transfer bookkeeping: each prompt's flight is tracked
  prefill -> (shipment) -> decode; completed shipments wait in a
  bounded buffer until a decode worker has a free slot AND pages;
- backpressure: a decode side with no capacity stalls the ROUTER (the
  shipment buffer fills, new prefill assignment pauses), never the
  prefill worker — in-flight prefills finish and park, and decode
  workers drain at their own pace. Counted in
  `serving_pod_backpressure_stalls_total`.

Workers are ordinary `Engine` instances (optionally mesh-sharded —
layer 1 composes under layer 2), driven synchronously by `step()`: the
router IS the schedule, so worker state never races and the whole pod
is deterministic on a seeded trace — which is how token-exactness
against a single-device engine is proven in tier-1. In-process workers
stand in for per-host processes; the shipment dataclass is the wire
format a multi-host deployment would serialize.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, AsyncIterator, Iterator

import jax
import numpy as np

from ...telemetry.export import start_metrics_server
from ...telemetry.registry import MetricsRegistry
from ...telemetry.trace import record_span
from ...telemetry.watchdog import StallWatchdog, resolve_stall_timeout
from ..engine import (
    Engine,
    EngineConfig,
    _as_raw_key,
    close_request_trace,
    prepare_request_tracing,
)
from ..metrics import ServingMetrics
from ..sanitizer import check_router, resolve_sanitize
from ..scheduler import (
    Request,
    RequestStatus,
    Scheduler,
    SHED_WORKER_DROP,
    SlotState,
)
from .mesh import shard_params, tensor_mesh
from .transfer import PageTransport, place_shipment

__all__ = ["PodConfig", "PodRouter", "PodEngine"]


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """Role split + transfer knobs for a disaggregated pod.

    `prefill_workers`/`decode_workers` are worker counts per role;
    `prefill_slots` sizes the prefill workers' slot tables (None = the
    engine config's num_slots — decode workers always use it).
    `tensor_parallel` > 1 additionally mesh-shards EVERY worker over
    that many devices (layer 1 under layer 2; in-process workers share
    one mesh and one placed copy of the params).
    `max_pending_shipments` bounds the prefill->decode buffer: when full
    the router stops assigning new prompts to prefill workers — the
    backpressure valve (None = one full decode worker's worth of
    slots, floor 2)."""

    prefill_workers: int = 1
    decode_workers: int = 1
    prefill_slots: int | None = None
    tensor_parallel: int = 1
    max_pending_shipments: int | None = None

    def __post_init__(self):
        if self.prefill_workers < 1 or self.decode_workers < 1:
            raise ValueError(
                "a pod needs at least one worker per role (got "
                f"prefill={self.prefill_workers}, "
                f"decode={self.decode_workers})")
        if self.tensor_parallel < 1:
            raise ValueError(
                f"tensor_parallel must be >= 1, got {self.tensor_parallel}")


class _FrontScheduler(Scheduler):
    """The pod's user-facing admission queue: the whole tenant/tier/DRR/
    SLO policy of the base scheduler with ZERO slots of its own — the
    router pops requests in policy order and places them on workers, so
    `live_slots`/`running` report the router's in-flight set (the server
    drive loop and drain path read these)."""

    def __init__(self, router: "PodRouter", **kwargs):
        super().__init__(num_slots=0, **kwargs)
        self._router = router

    @property
    def live_slots(self) -> int:  # type: ignore[override]
        return len(self._router._flights)

    def running(self):
        return [f.user for f in self._router._flights.values()]


@dataclasses.dataclass
class _Flight:
    """One user request's journey through the pod."""

    user: Request
    phase: str                    # "prefill" | "pending" | "decode"
    internal: Request | None = None
    worker: int = -1
    pages: list | None = None     # prefill-side allocation, recorded at admit
    shipment: Any = None
    copied: int = 0               # internal tokens mirrored to user so far


class PodRouter:
    """Disaggregated serving pod behind the `ServingEngine` API (see the
    module docstring for the architecture). Construct it exactly like an
    `Engine` — family, config, params, `EngineConfig` — plus a
    `PodConfig` for the role split."""

    def __init__(
        self,
        family,
        config,
        params,
        engine_config: EngineConfig | None = None,
        pod_config: PodConfig | None = None,
        clock=time.monotonic,
    ):
        self.config = config
        self.engine_config = ec = engine_config or EngineConfig()
        self.pod_config = pc = pod_config or PodConfig()
        self._clock = clock

        if ec.strict is not None and ec.strict not in ("warn", "error"):
            raise ValueError(
                f"strict must be None, 'warn', or 'error'; got {ec.strict!r}")

        # layer 1 under layer 2: one shared mesh + ONE placed params copy
        # (in-process workers alias the same arrays — a real pod gives
        # each worker its own slice and its own copy)
        mesh = None
        if pc.tensor_parallel > 1 or ec.mesh is not None:
            mesh = ec.mesh if ec.mesh is not None \
                else tensor_mesh(pc.tensor_parallel)
            params = shard_params(params, mesh)
        # workers own no observability side-cars: the pod facade is the
        # one exporter/watchdog surface (close() below stops the threads
        # the Engine constructor may have started from env config).
        # speculative is stripped: a spec worker's five-program surface
        # doesn't match the pod's extract/install protocol (the install
        # path drives the classic admit program directly) — pod +
        # speculation is a future arc, not a silent half-configuration
        worker_ec = dataclasses.replace(
            ec, mesh=mesh, tenants=None, metrics_port=None,
            watchdog_timeout_s=None, incident_dir=None, speculative=None)
        prefill_ec = dataclasses.replace(
            worker_ec, num_slots=pc.prefill_slots or ec.num_slots)

        def _make(worker_cfg):
            eng = Engine(family, config, params, worker_cfg, clock=clock)
            eng.close()  # stop any env-armed exporter/watchdog side-cars
            return eng

        self.prefill_workers = [_make(prefill_ec)
                                for _ in range(pc.prefill_workers)]
        self.decode_workers = [_make(worker_ec)
                               for _ in range(pc.decode_workers)]
        # hook every prefill worker's admission (Engine.on_admit): the
        # page allocation must be snapshotted the instant it exists — a
        # short prompt can admit, prefill, and retire inside ONE
        # engine.step(), and the alloc dies with the slot (the page
        # *content* survives until the next admission, which is the
        # window extract uses)
        for engine in self.prefill_workers:
            engine.on_admit = self._record_admit
        self._transports_p = [PageTransport(w) for w in self.prefill_workers]
        self._transports_d = [PageTransport(w) for w in self.decode_workers]

        self._sanitize = resolve_sanitize(ec.sanitize)
        self._flights: dict[int, _Flight] = {}   # id(user) -> flight
        # id(internal) -> page list, written by the admit hook the moment
        # a prefill worker maps the request (popped at harvest/cancel)
        self._admit_pages: dict[int, list] = {}
        self._pending: deque[_Flight] = deque()
        self._max_pending = pc.max_pending_shipments
        if self._max_pending is None:
            self._max_pending = max(2, ec.num_slots)

        self.scheduler = _FrontScheduler(
            self, max_len=ec.max_len, max_queue=ec.max_queue, clock=clock,
            tenants=ec.tenants, prefill_chunk=ec.prefill_chunk)
        self.registry = MetricsRegistry()
        self.metrics = ServingMetrics(registry=self.registry)
        self._c_shipments = self.registry.counter(
            "serving_pod_shipments_total")
        self._c_pages_shipped = self.registry.counter(
            "serving_pod_pages_shipped_total")
        self._c_stalls = self.registry.counter(
            "serving_pod_backpressure_stalls_total")
        self._c_affinity = self.registry.counter(
            "serving_pod_affinity_hits_total")
        self._g_pending = self.registry.gauge(
            "serving_pod_pending_shipments")
        self._g_occupancy = {
            role: self.registry.gauge("serving_pod_role_occupancy",
                                      role=role)
            for role in ("prefill", "decode")}
        self._g_pages_free = {
            role: self.registry.gauge("serving_pod_role_pages_free",
                                      role=role)
            for role in ("prefill", "decode")}
        self.metrics_server = start_metrics_server(
            ec.metrics_port, registry=self.registry)
        self.watchdog: StallWatchdog | None = None
        wd_timeout = resolve_stall_timeout(ec.watchdog_timeout_s)
        if wd_timeout is not None:
            self.watchdog = StallWatchdog(
                wd_timeout, name="serving-pod-router",
                incident_dir=ec.incident_dir, registry=self.registry,
                dumps=self.incident_dumps).start()
        self._base_key = jax.random.key(ec.seed)

    # -- request API (the ServingEngine surface) -----------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        key=None,
        eos_token_id: int | None = None,
        deadline_s: float | None = None,
        tenant: str = "default",
        slo_ttft_s: float | None = None,
        trace_id=None,
        trace_parent=0,
        trace_sampled: bool | None = None,
    ) -> Request:
        """`Engine.submit`, pod-routed: the handle returned is the live
        request object — tokens stream into it as decode workers produce
        them, overload is reported on it (REJECTED + shed_code +
        retry_after_s), and the trace identity is identical to the
        single-engine path."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(temperature), key=key,
            eos_token_id=eos_token_id, deadline_s=deadline_s,
            tenant=tenant, slo_ttft_s=slo_ttft_s,
        )
        prepare_request_tracing(req, trace_id, trace_parent, trace_sampled)
        # drain first, THEN capacity-check (the single engine's rule):
        # expired entries and assignable work must free queue positions
        # before the newcomer is judged against max_queue
        self.scheduler.shed_expired(self._clock())
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        self._assign_prefill()
        self.scheduler.submit(req)
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        if req.done:
            self._finalize(req)
        else:
            # eager assignment (the single engine admits eagerly too):
            # a free prefill slot starts the prompt now, not next step
            self._assign_prefill()
        return req

    def cancel(self, request: Request) -> bool:
        if request.done:
            return False
        if self.scheduler.cancel(request):        # still front-queued
            self._finalize(request)
            return True
        flight = self._flights.get(id(request))
        if flight is None:
            return False
        if flight.phase == "prefill":
            self.prefill_workers[flight.worker].cancel(flight.internal)
            self._admit_pages.pop(id(flight.internal), None)
        elif flight.phase == "decode":
            self._copy_tokens(flight)
            self.decode_workers[flight.worker].cancel(flight.internal)
        elif flight.phase == "pending":
            try:
                self._pending.remove(flight)
            except ValueError:
                pass
        del self._flights[id(request)]
        request.status = RequestStatus.CANCELLED
        request.finished_at = self._clock()
        self._finalize(request)
        return True

    def finish(self, request: Request) -> bool:
        """Retire a running request as FINISHED before its budget (the
        server's stop-sequence path) — tokens delivered so far stand."""
        if request.done:
            return False
        flight = self._flights.get(id(request))
        if flight is None:
            return False
        if flight.phase == "prefill":
            self.prefill_workers[flight.worker].cancel(flight.internal)
            self._admit_pages.pop(id(flight.internal), None)
        elif flight.phase == "decode":
            self._copy_tokens(flight)
            self.decode_workers[flight.worker].finish(flight.internal)
        elif flight.phase == "pending":
            try:
                self._pending.remove(flight)
            except ValueError:
                pass
        del self._flights[id(request)]
        request.status = RequestStatus.FINISHED
        request.finished_at = self._clock()
        self._finalize(request)
        return True

    def stream(self, request: Request) -> Iterator[int]:
        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
        yield from request.tokens[sent:]

    async def astream(self, request: Request) -> AsyncIterator[int]:
        import asyncio

        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
            await asyncio.sleep(0)
        for tok in request.tokens[sent:]:
            yield tok

    # -- the drive loop ------------------------------------------------------

    def step(self) -> bool:
        """One router round: shed, assign prompts to prefill workers,
        pump prefill (harvest finished prompts into shipments), land
        shipments on decode workers, pump decode (mirror tokens out).
        Returns False when the whole pod is idle."""
        if self.metrics.started_at is None:
            self.metrics.started_at = self._clock()
        if self.watchdog is not None:
            self.watchdog.tick()
        t0 = self._clock()
        self.scheduler.shed_expired(t0)
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        worked = self._assign_prefill()
        worked = self._pump_prefill() or worked
        worked = self._install_pending() or worked
        worked = self._pump_decode() or worked
        self._update_gauges()
        self.metrics.stopped_at = self._clock()
        if worked:
            self.scheduler.note_step_time(self.metrics.stopped_at - t0)
            live = sum(w.scheduler.live_slots for w in self.decode_workers)
            cap = sum(len(w.scheduler.slots) for w in self.decode_workers)
            self.metrics.observe_step(live, cap, self.scheduler.queue_depth)
        if self._sanitize:
            # router-level joins (flights vs pending vs admit snapshots
            # vs front queue); worker engines sanitize themselves inside
            # their own step()
            check_router(self)
        return worked

    def run_until_idle(self) -> None:
        while self.step():
            pass

    # -- role pumps ----------------------------------------------------------

    def _worker_capacity(self, engine: Engine) -> int:
        """Free prefill intake = idle slots minus already-queued work
        (the router only hands a worker what it can start now — ordering
        stays with the front queue's policy, not a worker FIFO)."""
        sched = engine.scheduler
        return (len(sched.slots) - sched.live_slots - sched.queue_depth)

    def _assign_prefill(self) -> bool:
        """Drain the front queue in policy order onto prefill workers.
        Stops at the backpressure bound: a full shipment buffer means the
        decode side owes us capacity, and prefilling further prompts
        would only pile pages up."""
        worked = False
        now = self._clock()
        while True:
            if len(self._pending) >= self._max_pending:
                # the stall itself is COUNTED in _install_pending (once
                # per router step, at the failed head placement) — this
                # site merely stops feeding the full buffer, and
                # incrementing here too would scale the counter with
                # submit rate instead of with stalled steps
                break
            name = self.scheduler._select_tenant()
            if name is None:
                break
            capacities = [self._worker_capacity(w)
                          for w in self.prefill_workers]
            widx = int(np.argmax(capacities))
            if capacities[widx] <= 0:
                break
            user = self.scheduler._pop_selected(name)
            user.status = RequestStatus.RUNNING
            user.admitted_at = now
            if user.trace_sampled:
                record_span("serving.queue_wait", user.submitted_at, now,
                            trace=user.trace_id, parent=user.span_id,
                            tenant=user.tenant)
            key_raw = _as_raw_key(user.key)
            if key_raw is None:
                # the single engine's derivation, verbatim: fold the seed
                # key with the request id — same seed + same trace =>
                # byte-identical sampling whether pod or single-device
                key_raw = jax.random.key_data(
                    jax.random.fold_in(self._base_key, user.request_id))
            engine = self.prefill_workers[widx]
            # budget 2 keeps the internal request RUNNING past its first
            # token (no self-retire inside engine.step), so its pages are
            # still mapped when the router extracts; the router then
            # finish_early()s it — unless the prompt is one token short
            # of max_len, where budget 1 is forced and the harvest relies
            # on extract-before-next-step (pages freed at retire are only
            # reallocatable at the NEXT admission)
            budget = 2 if user.prompt_len + 2 <= self.engine_config.max_len \
                else 1
            internal = engine.submit(
                user.prompt, max_new_tokens=budget,
                temperature=user.temperature, key=key_raw,
                trace_sampled=False)
            flight = _Flight(user=user, phase="prefill", internal=internal,
                             worker=widx)
            self._flights[id(user)] = flight
            if internal.done:
                # defensive: the engine refused our internal (can't
                # happen under the capacity/budget math above, but a
                # silent drop must not strand the user handle)
                self._harvest(engine, widx)
            worked = True
        return worked

    def _record_admit(self, slot, req) -> None:
        """Prefill workers' `Engine.on_admit` hook: snapshot every
        admission's page list (prefill workers serve only router
        internals, so recording all admissions is recording ours — and
        it works even when the admit happens inside `engine.submit`,
        before the flight object exists)."""
        self._admit_pages[id(req)] = list(slot.alloc.pages)

    def _pump_prefill(self) -> bool:
        worked = False
        for widx, engine in enumerate(self.prefill_workers):
            if engine.scheduler.has_work():
                engine.step()
                worked = True
            self._harvest(engine, widx)
        return worked

    def _harvest(self, engine: Engine, widx: int) -> None:
        """Collect internals whose prompt finished prefilling on this
        worker: deliver the first token to the user (TTFT lands here),
        extract the prompt's pages into a shipment — or finish the user
        outright when the first token already completes the request
        (budget 1, or EOS on the first token: nothing to ship)."""
        now = self._clock()
        for flight in list(self._flights.values()):
            if flight.phase != "prefill" or flight.worker != widx:
                continue
            internal, user = flight.internal, flight.user
            if not internal.tokens and not internal.done:
                continue
            if internal.done and internal.status is not RequestStatus.FINISHED:
                # the internal died (can't happen via router policy, but
                # a worker-side wedge must not strand the user request)
                self._admit_pages.pop(id(internal), None)
                del self._flights[id(user)]
                user.status = RequestStatus.EXPIRED
                user.reject_reason = (
                    f"prefill worker {widx} dropped the request "
                    f"({internal.status.value})")
                # every shed carries the machine-readable vocabulary +
                # a backoff hint — this path undercounted both (the
                # ATP212 self-lint finding)
                user.shed_code = SHED_WORKER_DROP
                user.retry_after_s = self.scheduler.retry_after_estimate()
                user.finished_at = now
                self._finalize(user)
                continue
            first = int(internal.tokens[0])
            flight.pages = self._admit_pages.pop(id(internal), None)
            user.tokens.append(first)
            if internal.logprobs:
                user.logprobs.append(internal.logprobs[0])
            user.token_times.append(now)
            user.first_token_at = now
            done = (user.max_new_tokens <= 1
                    or (user.eos_token_id is not None
                        and first == user.eos_token_id))
            if done:
                if not internal.done:
                    engine.finish(internal)
                del self._flights[id(user)]
                user.status = RequestStatus.FINISHED
                user.finished_at = now
                self._finalize(user)
                continue
            shipment = self._transports_p[widx].extract_shipment(
                flight.pages, internal, src_worker=widx, extracted_at=now)
            shipment.max_new_tokens = user.max_new_tokens
            shipment.eos_token_id = user.eos_token_id
            if not internal.done:
                # retire as FINISHED: the prompt's pages enter this
                # worker's prefix tree, so shared prefixes prefill once
                # per WORKER, not once per request
                engine.finish(internal)
            flight.phase = "pending"
            flight.internal = None
            flight.shipment = shipment
            self._pending.append(flight)

    def _install_pending(self) -> bool:
        """Land shipments on decode workers, strictly FIFO — the head
        shipment tries every worker, and if none has a slot AND pages the
        router waits (no skip-ahead: a big request must not starve behind
        luckier small ones). This is the backpressure point: the decode
        side stalls the ROUTER's buffer, never a prefill worker — and the
        ONLY place the stall counter increments (at most once per router
        step), so `serving_pod_backpressure_stalls_total` counts stalled
        steps, not client submit attempts."""
        worked = False
        while self._pending:
            flight = self._pending[0]
            if flight.user.done:           # cancelled while parked
                self._pending.popleft()
                continue
            placed = self._try_install(flight)
            if not placed:
                self._c_stalls.inc()
                break
            self._pending.popleft()
            worked = True
        return worked

    def _try_install(self, flight: _Flight) -> bool:
        user, shipment = flight.user, flight.shipment
        # prefix affinity: a worker whose radix tree already holds this
        # prompt's prefix turns the shipment's leading pages into a local
        # hit (HBM: free; host tier: one swap-in's worth of reserve, and
        # the shipment bytes overwrite the reserved pages value-exactly,
        # so the mirror is just dropped). HBM residency outranks host,
        # residency outranks emptiness; ties fall back to least-loaded.
        # residency_probe never touches LRU order — probing every worker
        # must not manufacture recency for the losers.
        scores = []
        for w in self.decode_workers:
            hbm = host = 0
            if w.allocator.index is not None:
                hbm, host = w.allocator.index.residency_probe(
                    shipment.prompt)
            scores.append(2 * hbm + host)
        order = sorted(
            range(len(self.decode_workers)),
            key=lambda i: (-scores[i],
                           -self.decode_workers[i].allocator.pages_free))
        for widx in order:
            engine = self.decode_workers[widx]
            # clock BEFORE the page reservation: placement owns the whole
            # allocate->adopt->install sequence (shared with the
            # multi-host worker's install handler — see
            # transfer.place_shipment)
            now = self._clock()
            placed = place_shipment(
                engine, self._transports_d[widx], shipment, now)
            if placed is None:
                continue
            internal, _slot, _alloc = placed
            if scores[widx] > 0:
                self._c_affinity.inc()
            flight.phase = "decode"
            flight.worker = widx
            flight.internal = internal
            flight.copied = 1
            self._c_shipments.inc()
            self._c_pages_shipped.inc(shipment.n_prompt_pages)
            if user.trace_sampled:
                record_span(
                    "serving.page_transfer", shipment.extracted_at, now,
                    trace=user.trace_id, parent=user.span_id,
                    pages=shipment.n_prompt_pages,
                    bytes=shipment.page_bytes,
                    src_worker=shipment.src_worker, dst_worker=widx)
            flight.shipment = None
            return True
        return False

    def _copy_tokens(self, flight: _Flight) -> None:
        internal, user = flight.internal, flight.user
        while flight.copied < len(internal.tokens):
            user.tokens.append(internal.tokens[flight.copied])
            if flight.copied < len(internal.logprobs):
                user.logprobs.append(internal.logprobs[flight.copied])
            user.token_times.append(internal.token_times[flight.copied])
            flight.copied += 1

    def _pump_decode(self) -> bool:
        worked = False
        for widx, engine in enumerate(self.decode_workers):
            if engine.scheduler.has_work():
                engine.step()
                worked = True
        for flight in list(self._flights.values()):
            if flight.phase != "decode":
                continue
            self._copy_tokens(flight)
            internal, user = flight.internal, flight.user
            if internal.done:
                del self._flights[id(user)]
                user.status = internal.status
                user.finished_at = internal.finished_at
                self._finalize(user)
        return worked

    def _finalize(self, req: Request) -> None:
        """The pod's one terminal path (mirror of
        Engine._finalize_request): close the request's trace, fold it
        into the pod-level metrics."""
        end = req.finished_at
        if end is None:
            end = self._clock()
        close_request_trace(req, end)
        self.metrics.observe_request(req)

    # -- metrics / observability ---------------------------------------------

    def _update_gauges(self) -> None:
        self._g_pending.set(len(self._pending))
        for role, workers in (("prefill", self.prefill_workers),
                              ("decode", self.decode_workers)):
            cap = sum(len(w.scheduler.slots) for w in workers)
            live = sum(w.scheduler.live_slots for w in workers)
            self._g_occupancy[role].set(live / max(1, cap))
            self._g_pages_free[role].set(
                sum(w.allocator.pages_free for w in workers))

    def compile_stats(self) -> dict[str, int]:
        """Per-program compile counts, aggregated as the MAX across the
        workers of each role — flat per role is the pod's recompile
        guard (a single worker creeping means its sharding layout lost
        its fixed point)."""
        out = {"admit": 0, "prefill": 0, "decode": 0, "extract": 0,
               "install": 0}
        for w in self.prefill_workers + self.decode_workers:
            for k, v in w.compile_stats().items():
                out[k] = max(out[k], v)
        for t in self._transports_p + self._transports_d:
            for k, v in t.compile_stats().items():
                out[k] = max(out[k], v)
        return out

    def metrics_summary(self) -> dict[str, float]:
        out = self.metrics.summary()
        # step/page counters live in the WORKER engines (the pod-level
        # ServingMetrics only sees request terminals): aggregate them so
        # the summary reads like a single engine's
        out["prefill_chunks"] = float(sum(
            w.metrics.prefill_chunks for w in self.prefill_workers))
        out["decode_steps"] = float(sum(
            w.metrics.decode_steps for w in self.decode_workers))
        out["pages_in_use"] = float(sum(
            w.allocator.pages_in_use
            for w in self.prefill_workers + self.decode_workers))
        out["pages_free"] = float(sum(
            w.allocator.pages_free
            for w in self.prefill_workers + self.decode_workers))
        out.update({f"compiles_{k}": float(v)
                    for k, v in self.compile_stats().items()})
        out["pod_shipments"] = float(self._c_shipments.value)
        out["pod_pages_shipped"] = float(self._c_pages_shipped.value)
        out["pod_backpressure_stalls"] = float(self._c_stalls.value)
        out["pod_affinity_hits"] = float(self._c_affinity.value)
        workers = self.prefill_workers + self.decode_workers
        swap_out = sum(w.metrics.swap_out_pages for w in workers)
        swap_in = sum(w.metrics.swap_in_pages for w in workers)
        if swap_out or swap_in:
            out["swap_out_pages"] = float(swap_out)
            out["swap_in_pages"] = float(swap_in)
            out["host_tier_pages_in_use"] = float(sum(
                w._host_tier.pages_in_use for w in workers
                if w._host_tier is not None))
        dedup = sum(w.metrics.prefix_dedup_hits for w in workers)
        if dedup:
            out["prefix_dedup_hits"] = float(dedup)
        return out

    def reset_metrics(self) -> None:
        """Drop accumulated samples; compiled programs, worker state and
        in-flight requests are untouched (same contract as the engine)."""
        self.registry.reset()
        self.metrics = ServingMetrics(registry=self.registry)
        self.scheduler.step_time_ema = 0.0
        for w in self.prefill_workers + self.decode_workers:
            w.reset_metrics()

    def close(self) -> None:
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        for w in self.prefill_workers + self.decode_workers:
            w.close()

    # -- introspection (the /debug endpoints) --------------------------------

    def debug_requests(self) -> dict:
        now = self._clock()
        return {
            "queued": [Engine._request_info(r, now)
                       for r in self.scheduler.queue],
            "running": [dict(Engine._request_info(f.user, now),
                             phase=f.phase)
                        for f in self._flights.values()],
        }

    def debug_slots(self) -> list[dict]:
        out = []
        for role, workers in (("prefill", self.prefill_workers),
                              ("decode", self.decode_workers)):
            for widx, w in enumerate(workers):
                for entry in w.debug_slots():
                    entry.update({"role": role, "worker": widx})
                    out.append(entry)
        return out

    def debug_pages(self) -> dict:
        out: dict[str, Any] = {"workers": []}
        for role, workers in (("prefill", self.prefill_workers),
                              ("decode", self.decode_workers)):
            for widx, w in enumerate(workers):
                row = w.debug_pages()
                row.update({"role": role, "worker": widx})
                out["workers"].append(row)
        out["pages_shipped"] = int(self._c_pages_shipped.value)
        out["pending_shipments"] = len(self._pending)
        return out

    def debug_scheduler(self) -> dict:
        out = self.scheduler.debug_state()
        out["pod"] = {
            "in_flight": len(self._flights),
            "pending_shipments": len(self._pending),
        }
        return out

    def debug_pod(self) -> dict:
        """Role/router state for the `/debug/pod` route: who holds what,
        how full the shipment buffer is, whether backpressure has been
        biting. Read-only, JSON-safe."""
        pc = self.pod_config
        roles: dict[str, list] = {"prefill": [], "decode": []}
        for role, workers in (("prefill", self.prefill_workers),
                              ("decode", self.decode_workers)):
            for widx, w in enumerate(workers):
                roles[role].append({
                    "worker": widx,
                    "slots": len(w.scheduler.slots),
                    "live_slots": w.scheduler.live_slots,
                    "queue_depth": w.scheduler.queue_depth,
                    "pages_free": w.allocator.pages_free,
                    "pages_in_use": w.allocator.pages_in_use,
                    "compiles": w.compile_stats(),
                })
        phases: dict[str, int] = {}
        for f in self._flights.values():
            phases[f.phase] = phases.get(f.phase, 0) + 1
        return {
            "roles": roles,
            "tensor_parallel": pc.tensor_parallel,
            "in_flight": phases,
            "queued": self.scheduler.queue_depth,
            "pending_shipments": len(self._pending),
            "max_pending_shipments": self._max_pending,
            "shipments_total": int(self._c_shipments.value),
            "pages_shipped_total": int(self._c_pages_shipped.value),
            "backpressure_stalls_total": int(self._c_stalls.value),
        }

    def incident_dumps(self) -> dict:
        out: dict[str, Any] = {}
        for name, build in (
            ("pod", self.debug_pod),
            ("requests", self.debug_requests),
            ("scheduler", self.debug_scheduler),
            ("compile_stats", self.compile_stats),
        ):
            try:
                out[name] = build()
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out


# the facade name mirrors serving.ServingEngine: same API, pod-backed
PodEngine = PodRouter
