"""Layer 3: the pod across OS processes — wire transport, worker
heartbeats + failure recovery, elastic prefill/decode rebalancing.

PR 9's in-process `PodRouter` stays the `local` transport; this package
is the same dataflow over real process boundaries:

- `wire` — length-prefixed frames (JSON header + raw numpy buffers, no
  pickle) carrying the existing fixed-shape `KVPageShipment`
  codes+scales format and all control traffic.
- `transport` — `LocalChannel` (in-process, still through the codec),
  `SocketChannel` (bounded send queue = backpressure stalls the router,
  never a prefill worker), `FlakyTransport` (deterministic fault
  injection: drop/dup/delay/reorder, kill/hang).
- `worker` — `WorkerServer`: one role-agnostic Engine behind a channel;
  heartbeats carry stats + the registry snapshot; SIGTERM drains.
- `droute` — `DistributedPodRouter`: the `ServingEngine`-API front that
  holds no device state, recovers every failure by
  re-prefill-from-prompt (byte-exact via position-folded sampling
  keys), and converts idle workers between roles from live load.

See docs/serving.md "True multi-host pod".
"""

from .droute import (
    DistributedPodConfig,
    DistributedPodRouter,
    WorkerHandle,
    build_local_distributed_pod,
)
from .transport import (
    Channel,
    ChannelListener,
    FlakyTransport,
    LocalChannel,
    SocketChannel,
)
from .wire import (
    Message,
    decode_message,
    encode_message,
    shipment_from_message,
    shipment_to_message,
)
from .worker import WorkerServer, build_worker_engine

__all__ = [
    "DistributedPodConfig",
    "DistributedPodRouter",
    "WorkerHandle",
    "build_local_distributed_pod",
    "Channel",
    "ChannelListener",
    "FlakyTransport",
    "LocalChannel",
    "SocketChannel",
    "Message",
    "encode_message",
    "decode_message",
    "shipment_to_message",
    "shipment_from_message",
    "WorkerServer",
    "build_worker_engine",
]
