"""DistributedPodRouter: the multi-host pod front, behind the same API.

PR 9's `PodRouter` proved the disaggregated dataflow (prefill workers
produce KV page shipments, decode workers own slots) inside one process.
This router runs the SAME dataflow over channels: workers are separate
OS processes reached through `SocketChannel`s (or in-process
`WorkerServer`s over `LocalChannel`s — the deterministic test form), and
the router holds no model, no params, no device state — it is pure
bookkeeping plus the user-facing scheduler, which is exactly what lets
it survive any worker dying.

Exactness is inherited, not engineered: sampling keys fold the request
key with the ABSOLUTE position (`engine.sample_slot`), so token `i` of a
request is a pure function of (params, prompt, key, position) — the same
schedule-independence that made the in-process pod byte-identical to the
single engine makes the process boundary invisible, and makes recovery a
replay: re-prefilling `prompt + delivered_tokens` with the original key
samples its "first token" at position `prompt_len + d`, which IS token
`d` of the original stream. Delivered tokens stand; the continuation is
byte-identical; nothing is lost or duplicated.

Failure model (every path funnels into `_replay_flight`):

- dropped connection  -> worker lost immediately (`channel_drop`)
- missed heartbeats   -> worker lost after `heartbeat_timeout_s`
  (`heartbeat_timeout` — the hung-but-connected case)
- stalled flight      -> no progress for `flight_timeout_s` while the
  worker looks alive (`stalled` — a dropped submit/shipment/tokens
  message on a lossy transport); the old attempt is cancelled
- worker refuses an install -> `install_refused`; worker kills an
  internal -> `worker_drop`; each replay bumps `attempt`, so stale
  messages from superseded attempts are recognized and dropped
- a flight that exhausts `max_attempts` is shed with the PR 9 shed
  vocabulary (`SHED_WORKER_DROP` + retry_after) instead of looping

Every recovery appends a `recovery_log` entry with its shed-code-style
`recovery_reason` and bumps `serving_pod_worker_{lost,recovered}_total`
/ `serving_pod_requests_replayed_total`; recovery latency (loss detected
-> replayed stream's next token delivered) lands in the
`serving_pod_recovery_latency_seconds` sketch.

Elastic rebalancing replaces the config-time role ratio: roles are SOFT
labels the router flips on idle workers from live queue-depth/occupancy
signals, hysteresis-banded (`occupancy_low` .. `occupancy_high` is a
dead zone, so it cannot flap) and bounded to one conversion per
`rebalance_window_s`. Soft roles are also the last line of recovery: if
a role has NO alive workers, any alive worker takes its work — a pod
reduced to one surviving worker keeps serving.

Backpressure is unchanged from PR 9: the router's pending-shipment
buffer is bounded (`_assign_prefill` stops feeding when full) and
`SocketChannel.send` blocks on a full send queue — the decode side
stalls the ROUTER, never a prefill worker.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, AsyncIterator, Iterator

import numpy as np

from ....telemetry.aggregate import merged_registry
from ....telemetry.export import start_metrics_server
from ....telemetry.registry import MetricsRegistry
from ....telemetry.trace import export_chrome_trace, ingest_spans, record_span
from ....telemetry.watchdog import (
    StallWatchdog,
    resolve_incident_dir,
    resolve_stall_timeout,
    write_incident_bundle,
)
from ...engine import (
    EngineConfig,
    _as_raw_key,
    close_request_trace,
    prepare_request_tracing,
)
from ...metrics import ServingMetrics
from ...sanitizer import check_distributed_router, resolve_sanitize
from ...scheduler import Request, RequestStatus, SHED_WORKER_DROP
from ..router import _FrontScheduler
from ..transfer import KVPageShipment
from .transport import Channel, ChannelListener
from .wire import (
    Message,
    shipment_from_message,
    shipment_to_message,
    trace_meta,
)
from .worker import WorkerServer

__all__ = ["DistributedPodConfig", "DistributedPodRouter", "WorkerHandle",
           "build_local_distributed_pod"]

# recovery_reason vocabulary (shed-code style: machine-readable, stable)
RECOVER_CHANNEL_DROP = "channel_drop"
RECOVER_HEARTBEAT_TIMEOUT = "heartbeat_timeout"
RECOVER_STALLED = "stalled"
RECOVER_INSTALL_REFUSED = "install_refused"
RECOVER_WORKER_DROP = "worker_drop"
RECOVER_WORKER_DRAINED = "worker_drained"
RECOVER_GAVE_UP = "gave_up"


@dataclasses.dataclass(frozen=True)
class DistributedPodConfig:
    """Knobs for the multi-host pod front (`PodConfig`'s distributed
    sibling). Timeouts are generous by default — CPU-test prefills are
    slow; production tightens them."""

    prefill_workers: int = 1
    decode_workers: int = 1
    max_pending_shipments: int | None = None
    heartbeat_interval_s: float = 0.25
    heartbeat_timeout_s: float = 5.0
    # a flight with no progress for this long while its worker still
    # heartbeats -> the message (not the worker) was lost: replay
    flight_timeout_s: float = 60.0
    max_attempts: int = 5
    rebalance: bool = True
    rebalance_window_s: float = 10.0
    occupancy_high: float = 0.85
    occupancy_low: float = 0.25
    # a worker whose last heartbeat said `busy` (first-compile, long
    # device block) gets THIS silence budget instead of
    # heartbeat_timeout_s — busy-not-dead must not be a phantom loss,
    # which is what lets heartbeat_timeout_s itself stay tight
    busy_heartbeat_timeout_s: float = 300.0
    # fleet incident bundles: per-worker incident_dumps RPC wall-clock
    # budget, and the write rate limit (a flake storm must not turn the
    # incident dir into a DoS on its own disk)
    incident_rpc_timeout_s: float = 2.0
    fleet_bundle_min_interval_s: float = 30.0
    # lost workers' metric snapshots are served labeled stale="true";
    # set a horizon (seconds since last heartbeat) to drop them entirely
    snapshot_stale_after_s: float | None = None

    def __post_init__(self):
        if self.prefill_workers < 1 or self.decode_workers < 1:
            raise ValueError("a pod needs at least one worker per role")
        if not (0.0 <= self.occupancy_low < self.occupancy_high <= 1.0):
            raise ValueError(
                "rebalance bands must satisfy 0 <= low < high <= 1 (got "
                f"low={self.occupancy_low}, high={self.occupancy_high})")
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")


@dataclasses.dataclass
class WorkerHandle:
    """Router-side view of one worker process."""

    worker_id: int
    channel: Channel
    role: str                         # SOFT label; router-authoritative
    slots: int
    alive: bool = False               # True after hello/first heartbeat
    lost: bool = False
    draining: bool = False
    busy: bool = False                # last heartbeat announced a long block
    last_heartbeat: float = 0.0
    stats: dict = dataclasses.field(default_factory=dict)
    compiles: dict = dataclasses.field(default_factory=dict)
    snapshot: dict | None = None      # last heartbeat's registry snapshot
    snapshot_at: float = 0.0          # router clock at snapshot receipt
    pid: int | None = None
    # NTP-style clock estimate (router clock MINUS worker clock) from
    # heartbeat round trips, EWMA-smoothed; error is bounded by +-RTT/2
    clock_offset_s: float | None = None
    clock_rtt_s: float | None = None
    span_seq: int = 0                 # span-export high-water (dedup)
    last_span_at: float | None = None  # router clock of last span ingest
    local: Any = None                 # in-process WorkerServer to pump


@dataclasses.dataclass
class _DFlight:
    """One user request's journey, replay-aware. Phases:
    replay -> prefill -> pending -> decode (replay re-enters at replay)."""

    user: Request
    flight_id: int
    key_raw: np.ndarray
    attempt: int = 1
    phase: str = "replay"
    worker: int = -1                  # worker_id, -1 while router-held
    shipment: KVPageShipment | None = None
    copied: int = 0                   # internal tokens mirrored (decode)
    base: int = 0                     # user tokens delivered before attempt
    progress_at: float = 0.0
    replay_started_at: float | None = None
    dispatch_span: int = 0            # span id of this attempt's dispatch
    #                                   (a replay span links its original)


class DistributedPodRouter:
    """Multi-host pod front behind the `ServingEngine` API."""

    def __init__(
        self,
        engine_config: EngineConfig | None = None,
        pod_config: DistributedPodConfig | None = None,
        clock=time.monotonic,
        listener: ChannelListener | None = None,
    ):
        self.engine_config = ec = engine_config or EngineConfig()
        self.pod_config = pc = pod_config or DistributedPodConfig()
        self._clock = clock
        self.listener = listener
        self._unclaimed: list[Channel] = []

        self._sanitize = resolve_sanitize(ec.sanitize)
        self.workers: dict[int, WorkerHandle] = {}
        self._flights: dict[int, _DFlight] = {}        # flight_id -> flight
        self._by_user: dict[int, _DFlight] = {}        # id(user) -> flight
        self._pending: deque[int] = deque()            # flight_ids
        self._replay: deque[int] = deque()             # flight_ids
        self._next_flight_id = 1
        self._max_pending = pc.max_pending_shipments
        if self._max_pending is None:
            self._max_pending = max(2, ec.num_slots)
        # start the rebalance window NOW: converting on the first step
        # (queue pressure exists before decode occupancy can) would
        # reshape the pod before it ever ran its configured shape
        self._last_rebalance = self._clock()
        self.last_step_worked = False
        self.recovery_log: deque[dict] = deque(maxlen=256)

        self.scheduler = _FrontScheduler(
            self, max_len=ec.max_len, max_queue=ec.max_queue, clock=clock,
            tenants=ec.tenants, prefill_chunk=ec.prefill_chunk)
        self.registry = MetricsRegistry()
        self.metrics = ServingMetrics(registry=self.registry)
        reg = self.registry
        self._c_shipments = reg.counter("serving_pod_shipments_total")
        self._c_pages_shipped = reg.counter("serving_pod_pages_shipped_total")
        self._c_stalls = reg.counter("serving_pod_backpressure_stalls_total")
        self._c_lost = reg.counter("serving_pod_worker_lost_total")
        self._c_recovered = reg.counter("serving_pod_worker_recovered_total")
        self._c_replayed = reg.counter("serving_pod_requests_replayed_total")
        self._c_stale = reg.counter("serving_pod_stale_messages_total")
        self._c_conversions = {
            d: reg.counter("serving_pod_role_conversions_total", direction=d)
            for d in ("prefill_to_decode", "decode_to_prefill")}
        self._h_recovery = reg.histogram(
            "serving_pod_recovery_latency_seconds")
        self._c_spans = reg.counter("serving_pod_worker_spans_ingested_total")
        self._g_pending = reg.gauge("serving_pod_pending_shipments")
        self._g_alive = reg.gauge("serving_pod_workers_alive")
        self._g_clock_offset: dict[int, Any] = {}  # worker_id -> gauge
        self._g_occupancy = {
            role: reg.gauge("serving_pod_role_occupancy", role=role)
            for role in ("prefill", "decode")}
        self.metrics_server = start_metrics_server(
            ec.metrics_port, registry=self.registry)
        # fleet incident bundles: triggered by loss/recovery/sanitizer
        # events, written at the END of the triggering step (never from
        # inside dispatch — the RPC fan-out re-enters the poll loop)
        self._incident_dir = resolve_incident_dir(ec.incident_dir)
        self._pending_incident: tuple[str, str] | None = None
        self._last_fleet_bundle: float | None = None
        self._incident_seq = 0
        self._incident_replies: dict[tuple[int, int], dict] = {}
        self.watchdog: StallWatchdog | None = None
        wd_timeout = resolve_stall_timeout(ec.watchdog_timeout_s)
        if wd_timeout is not None:
            self.watchdog = StallWatchdog(
                wd_timeout, name="serving-pod-droute",
                incident_dir=ec.incident_dir, registry=self.registry,
                dumps=self.incident_dumps).start()
        import jax

        self._base_key = jax.random.key(ec.seed)

    # -- worker registration -------------------------------------------------

    def register_worker(self, channel: Channel, worker_id: int, role: str,
                        slots: int | None = None,
                        local: "WorkerServer | None" = None) -> WorkerHandle:
        """Attach a worker the router already knows the identity of
        (in-process factories, pre-spawned CLI workers). Socket workers
        that dial the listener instead self-identify via `hello`."""
        handle = WorkerHandle(
            worker_id=int(worker_id), channel=channel, role=role,
            slots=slots if slots is not None else self.engine_config.num_slots,
            last_heartbeat=self._clock(), local=local)
        self.workers[handle.worker_id] = handle
        return handle

    # -- request API (the ServingEngine surface) -----------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        key=None,
        eos_token_id: int | None = None,
        deadline_s: float | None = None,
        tenant: str = "default",
        slo_ttft_s: float | None = None,
        trace_id=None,
        trace_parent=0,
        trace_sampled: bool | None = None,
    ) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(temperature), key=key,
            eos_token_id=eos_token_id, deadline_s=deadline_s,
            tenant=tenant, slo_ttft_s=slo_ttft_s,
        )
        prepare_request_tracing(req, trace_id, trace_parent, trace_sampled)
        self.scheduler.shed_expired(self._clock())
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        self._assign_prefill()
        self.scheduler.submit(req)
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        if req.done:
            self._finalize(req)
        else:
            self._assign_prefill()
        return req

    def cancel(self, request: Request) -> bool:
        if request.done:
            return False
        if self.scheduler.cancel(request):
            self._finalize(request)
            return True
        flight = self._by_user.get(id(request))
        if flight is None:
            return False
        self._retire_flight(flight, notify="cancel")
        request.status = RequestStatus.CANCELLED
        request.finished_at = self._clock()
        self._finalize(request)
        return True

    def finish(self, request: Request) -> bool:
        if request.done:
            return False
        flight = self._by_user.get(id(request))
        if flight is None:
            return False
        self._retire_flight(flight, notify="finish")
        request.status = RequestStatus.FINISHED
        request.finished_at = self._clock()
        self._finalize(request)
        return True

    def _retire_flight(self, flight: _DFlight, notify: str) -> None:
        """Drop a flight from every router structure and (best-effort)
        tell its worker to free the slot."""
        if flight.phase == "pending":
            try:
                self._pending.remove(flight.flight_id)
            except ValueError:
                pass
        elif flight.phase == "replay":
            try:
                self._replay.remove(flight.flight_id)
            except ValueError:
                pass
        elif flight.worker in self.workers:
            handle = self.workers[flight.worker]
            if handle.alive:
                try:
                    handle.channel.send(Message(notify, {
                        "flight_id": flight.flight_id,
                        "attempt": flight.attempt}))
                except ConnectionError:
                    pass  # failure detection will reap the worker
        self._flights.pop(flight.flight_id, None)
        self._by_user.pop(id(flight.user), None)

    def stream(self, request: Request) -> Iterator[int]:
        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
        yield from request.tokens[sent:]

    async def astream(self, request: Request) -> AsyncIterator[int]:
        import asyncio

        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
            # idle-but-outstanding on a pure-remote pod: yield a real
            # tick so the reader threads can land replies; otherwise
            # just yield the loop
            await asyncio.sleep(
                0 if self.last_step_worked or self._has_local_workers()
                else 0.001)
        for tok in request.tokens[sent:]:
            yield tok

    # -- the drive loop ------------------------------------------------------

    def step(self) -> bool:
        """One router round: accept joiners, dispatch worker messages,
        detect failures, replay, assign, forward, rebalance, pump local
        workers. Returns False only when the whole pod is idle — while
        flights are outstanding on remote workers it returns True even
        if nothing moved this instant (the work is elsewhere)."""
        if self.metrics.started_at is None:
            self.metrics.started_at = self._clock()
        if self.watchdog is not None:
            self.watchdog.tick()
        t0 = self._clock()
        self.scheduler.shed_expired(t0)
        for victim in self.scheduler.drain_shed():
            self._finalize(victim)
        self._accept_joiners()
        worked = self._dispatch_inbound()
        self._detect_failures()
        self._watch_flights()
        worked = self._assign_prefill() or worked
        worked = self._forward_pending() or worked
        self._rebalance()
        for handle in self.workers.values():
            if handle.local is not None and not handle.lost:
                worked = handle.local.run_once() or worked
        self._update_gauges()
        self.metrics.stopped_at = self._clock()
        if worked:
            self.scheduler.note_step_time(self.metrics.stopped_at - t0)
            live = len([f for f in self._flights.values()
                        if f.phase == "decode"])
            cap = sum(h.slots for h in self.workers.values()
                      if h.alive and h.role == "decode") or 1
            self.metrics.observe_step(live, cap, self.scheduler.queue_depth)
        # fleet bundles write at the END of the step: the RPC fan-out
        # re-enters the poll loop, which must not happen inside dispatch
        self._maybe_write_fleet_bundle()
        if self._sanitize:
            try:
                check_distributed_router(self)
            except Exception:
                self._note_incident("sanitizer_violation")
                self._maybe_write_fleet_bundle()
                raise
        outstanding = bool(self._flights) or self.scheduler.queue_depth > 0
        # pacing is the CALLER's job: step() runs inline on the asyncio
        # drive loop (astream), and a sleep here would park every task on
        # the loop. Sync callers read `last_step_worked` and sleep.
        self.last_step_worked = worked
        return worked or outstanding

    def run_until_idle(self) -> None:
        while self.step():
            if not self.last_step_worked and not self._has_local_workers():
                time.sleep(0.001)   # remote work in flight: don't spin hot

    def _has_local_workers(self) -> bool:
        return any(h.local is not None for h in self.workers.values())

    # -- inbound -------------------------------------------------------------

    def _accept_joiners(self) -> None:
        if self.listener is not None:
            self._unclaimed.extend(self.listener.accept_all())
        still: list[Channel] = []
        for ch in self._unclaimed:
            claimed = False
            for msg in ch.poll():
                if msg.kind == "hello":
                    self._claim(ch, msg.meta)
                    claimed = True
                # pre-hello chatter from an unclaimed channel is dropped
            if not claimed and not ch.closed:
                still.append(ch)
        self._unclaimed = still

    def _claim(self, channel: Channel, meta: dict) -> None:
        wid = int(meta["worker_id"])
        handle = self.workers.get(wid)
        if handle is None:
            self.workers[wid] = handle = WorkerHandle(
                worker_id=wid, channel=channel,
                role=str(meta.get("role", "decode")),
                slots=int(meta.get("slots", self.engine_config.num_slots)))
        else:
            # rejoin on a fresh connection: the router already replayed
            # everything this worker held — wipe its local state and
            # re-impose the router-authoritative role label
            handle.channel = channel
            try:
                channel.send(Message("reset", {}))
                channel.send(Message("set_role", {"role": handle.role}))
            except ConnectionError:
                return
        handle.slots = int(meta.get("slots", handle.slots))
        self._mark_alive(handle)

    def _mark_alive(self, handle: WorkerHandle) -> None:
        handle.last_heartbeat = self._clock()
        if handle.lost:
            handle.lost = False
            self._c_recovered.inc()
        handle.alive = True

    def _dispatch_inbound(self) -> bool:
        worked = False
        for handle in list(self.workers.values()):
            if handle.channel.closed:
                continue
            for msg in handle.channel.poll():
                kind = msg.kind
                # heartbeats are liveness, not progress: counting them as
                # work would keep an idle pod's step() returning True
                worked = worked or kind not in ("heartbeat", "hello")
                if kind == "heartbeat":
                    self._on_heartbeat(handle, msg.meta)
                elif kind == "shipment":
                    self._on_shipment(handle, msg)
                elif kind == "tokens":
                    self._on_tokens(handle, msg.meta)
                elif kind == "install_failed":
                    self._on_flight_refusal(msg.meta, RECOVER_INSTALL_REFUSED,
                                            want_phase="decode")
                elif kind == "prefill_failed":
                    self._on_flight_refusal(msg.meta, RECOVER_WORKER_DROP,
                                            want_phase="prefill")
                elif kind == "hello":
                    handle.slots = int(msg.meta.get("slots", handle.slots))
                    self._mark_alive(handle)
                elif kind == "incident_dumps":
                    self._incident_replies[
                        (int(msg.meta.get("req_id") or 0),
                         handle.worker_id)] = msg.meta.get("dumps") or {}
                elif kind == "bye":
                    self._on_bye(handle)
        return worked

    def _on_heartbeat(self, handle: WorkerHandle, meta: dict) -> None:
        # heartbeat recency uses the ROUTER's receipt clock: worker
        # clocks are not comparable across hosts — which is exactly why
        # the same receipt stamp doubles as T4 of the NTP exchange below
        now = self._clock()
        was_lost = handle.lost
        self._mark_alive(handle)
        # lean busy announces omit stats/compiles/snapshot: only update
        # what this heartbeat actually carries
        if meta.get("stats") is not None:
            handle.stats = meta["stats"]
        if meta.get("compiles") is not None:
            handle.compiles = meta["compiles"]
        handle.busy = bool(meta.get("busy", False))
        if meta.get("pid") is not None:
            handle.pid = int(meta["pid"])
        if meta.get("snapshot") is not None:
            handle.snapshot = meta.get("snapshot")
            handle.snapshot_at = now
        handle.slots = int(handle.stats.get("slots", handle.slots))
        self._sync_worker_clock(handle, meta, now)
        self._ingest_worker_spans(handle, meta, now)
        try:
            # receipt stamp back to the worker; its echo on the NEXT
            # heartbeat closes the NTP round trip
            handle.channel.send(Message("hb_ack", {
                "worker_t": meta.get("t"), "router_t": now}))
        except ConnectionError:
            pass  # failure detection will reap the worker
        if was_lost:
            # rejoined after a partition the router recovered around:
            # its flights were replayed elsewhere — clear its state
            try:
                handle.channel.send(Message("reset", {}))
                handle.channel.send(
                    Message("set_role", {"role": handle.role}))
            except ConnectionError:
                pass

    def _sync_worker_clock(self, handle: WorkerHandle, meta: dict,
                           now: float) -> None:
        """NTP-style offset estimate from the heartbeat round trip. The
        worker echoes the router's last `hb_ack` (T1 = router send, T2 =
        worker receipt) alongside its own send stamp (T3); `now` is the
        router receipt (T4):

            offset(router - worker) = ((T1 - T2) + (T4 - T3)) / 2
            rtt = (T4 - T1) - (T3 - T2)

        Error is bounded by +-rtt/2; EWMA smoothing (alpha 0.25) rides
        out scheduling jitter. First contact has no echo yet — fall back
        to the one-way T4 - T3 (biased by the network delay; the first
        completed round trip corrects it). In-process workers short-
        circuit to offset 0 — they share the router's clock, and the
        estimator's "delay" would be whole engine steps."""
        if handle.local is not None:
            # in-process workers share this very clock: the estimator's
            # "network delay" would be whole engine steps (large, one-
            # sided), injecting error where the true offset is exactly 0
            handle.clock_offset_s = 0.0
            handle.clock_rtt_s = 0.0
        t3 = meta.get("t")
        if t3 is None:
            return
        t3 = float(t3)
        if handle.local is not None:
            self._set_clock_offset_gauge(handle)
            return
        ack = meta.get("ack") or {}
        t1, t2 = ack.get("router_t"), ack.get("worker_recv_t")
        if t1 is not None and t2 is not None:
            t1, t2 = float(t1), float(t2)
            rtt = (now - t1) - (t3 - t2)
            if rtt < 0:
                return   # a clock stepped mid-round: discard the sample
            handle.clock_rtt_s = (
                rtt if handle.clock_rtt_s is None
                else 0.75 * handle.clock_rtt_s + 0.25 * rtt)
            sample = ((t1 - t2) + (now - t3)) / 2.0
        elif handle.clock_offset_s is None:
            sample = now - t3
        else:
            return       # have a round-trip estimate; don't regress to one-way
        handle.clock_offset_s = (
            sample if handle.clock_offset_s is None
            else 0.75 * handle.clock_offset_s + 0.25 * sample)
        self._set_clock_offset_gauge(handle)

    def _set_clock_offset_gauge(self, handle: WorkerHandle) -> None:
        gauge = self._g_clock_offset.get(handle.worker_id)
        if gauge is None:
            gauge = self._g_clock_offset[handle.worker_id] = \
                self.registry.gauge(
                    "serving_pod_worker_clock_offset_seconds",
                    worker=str(handle.worker_id))
        gauge.set(handle.clock_offset_s)

    def _ingest_worker_spans(self, handle: WorkerHandle, meta: dict,
                             now: float) -> None:
        """Rebase a heartbeat's span batch into router time and index it.
        `span_seq` is the worker's export high-water mark — a duplicated
        heartbeat (at-least-once transports resend) must not double its
        spans."""
        spans = meta.get("spans")
        seq = int(meta.get("span_seq") or 0)
        if seq > handle.span_seq:
            handle.span_seq = seq
        elif spans:
            return
        if not spans:
            return
        n = ingest_spans(spans, offset_s=handle.clock_offset_s or 0.0,
                         pid=handle.pid, worker=handle.worker_id)
        if n:
            self._c_spans.inc(n)
            handle.last_span_at = now

    def _stale_msg(self, meta: dict, want_phase: str) -> "_DFlight | None":
        """Resolve a job-bearing message to its flight, or count it
        stale (unknown flight / superseded attempt / wrong phase)."""
        flight = self._flights.get(int(meta["flight_id"]))
        if (flight is None or int(meta["attempt"]) != flight.attempt
                or flight.phase != want_phase):
            self._c_stale.inc()
            return None
        return flight

    def _on_shipment(self, handle: WorkerHandle, msg: Message) -> None:
        flight = self._stale_msg(msg.meta, want_phase="prefill")
        if flight is None:
            return
        shipment = shipment_from_message(msg)
        now = self._clock()
        user = flight.user
        first = int(shipment.first_token)
        user.tokens.append(first)
        if shipment.first_logprob is not None:
            user.logprobs.append(float(shipment.first_logprob))
        user.token_times.append(now)
        if user.first_token_at is None:
            # replays keep the ORIGINAL TTFT — the user saw their first
            # token when they saw it; recovery shows up in recovery
            # latency, not a rewritten TTFT
            user.first_token_at = now
        if flight.replay_started_at is not None:
            self._h_recovery.record(now - flight.replay_started_at)
            flight.replay_started_at = None
        flight.progress_at = now
        done = (len(user.tokens) >= user.max_new_tokens
                or (user.eos_token_id is not None
                    and first == user.eos_token_id))
        if done:
            self._flights.pop(flight.flight_id, None)
            self._by_user.pop(id(user), None)
            user.status = RequestStatus.FINISHED
            user.finished_at = now
            self._finalize(user)
            return
        # the decode internal seeds the shipped first token via
        # note_token, so its budget counts from that token: remaining
        # stream = max_new minus tokens delivered BEFORE it
        flight.base = len(user.tokens) - 1
        shipment.max_new_tokens = user.max_new_tokens - flight.base
        shipment.eos_token_id = user.eos_token_id
        flight.phase = "pending"
        flight.worker = -1
        flight.shipment = shipment
        self._pending.append(flight.flight_id)

    def _on_tokens(self, handle: WorkerHandle, meta: dict) -> None:
        flight = self._stale_msg(meta, want_phase="decode")
        if flight is None:
            return
        user = flight.user
        toks, lps = meta["tokens"], meta["logprobs"]
        now = self._clock()
        # full-state sync: keep the longest prefix seen for this attempt
        # (idempotent under dup/reorder — a shorter late message is a
        # no-op, never a rewind)
        while flight.copied < len(toks):
            i = flight.copied
            user.tokens.append(int(toks[i]))
            if i < len(lps):
                user.logprobs.append(float(lps[i]))
            user.token_times.append(now)
            flight.copied += 1
        flight.progress_at = now
        if meta.get("done"):
            if meta.get("status") == RequestStatus.FINISHED.value:
                self._flights.pop(flight.flight_id, None)
                self._by_user.pop(id(user), None)
                user.status = RequestStatus.FINISHED
                user.finished_at = now
                self._finalize(user)
            else:
                # the worker's internal died under it — treat like a
                # worker drop of this one flight
                self._replay_flight(flight, RECOVER_WORKER_DROP)

    def _on_flight_refusal(self, meta: dict, reason: str,
                           want_phase: str) -> None:
        flight = self._stale_msg(meta, want_phase=want_phase)
        if flight is not None:
            self._replay_flight(flight, reason)

    def _on_bye(self, handle: WorkerHandle) -> None:
        handle.draining = True
        handle.alive = False
        for flight in [f for f in self._flights.values()
                       if f.worker == handle.worker_id
                       and f.phase in ("prefill", "decode")]:
            self._replay_flight(flight, RECOVER_WORKER_DRAINED)

    # -- failure detection & recovery ----------------------------------------

    def _detect_failures(self) -> None:
        now = self._clock()
        for handle in self.workers.values():
            if not handle.alive or handle.lost:
                continue
            if handle.channel.closed:
                self._lose_worker(handle, RECOVER_CHANNEL_DROP)
                continue
            timeout = self.pod_config.heartbeat_timeout_s
            if handle.busy:
                # the worker ANNOUNCED a long block (first compile, big
                # device step) before going quiet: busy-not-dead gets the
                # long rope, which is what lets the plain timeout stay
                # tight without phantom losses
                timeout = max(timeout,
                              self.pod_config.busy_heartbeat_timeout_s)
            if now - handle.last_heartbeat > timeout:
                self._lose_worker(handle, RECOVER_HEARTBEAT_TIMEOUT)

    def _lose_worker(self, handle: WorkerHandle, reason: str) -> None:
        handle.alive = False
        handle.lost = True
        self._c_lost.inc()
        self._note_incident(reason, f"fleet-loss-w{handle.worker_id}")
        for flight in [f for f in self._flights.values()
                       if f.worker == handle.worker_id
                       and f.phase in ("prefill", "decode")]:
            self._replay_flight(flight, reason)

    def _watch_flights(self) -> None:
        """A flight with no progress while its worker still heartbeats:
        the MESSAGE was lost, not the worker. Cancel the old attempt on
        the worker (frees its slot) and replay."""
        timeout = self.pod_config.flight_timeout_s
        if timeout is None or timeout <= 0:
            return
        now = self._clock()
        for flight in list(self._flights.values()):
            if flight.phase not in ("prefill", "decode"):
                continue
            if now - flight.progress_at <= timeout:
                continue
            handle = self.workers.get(flight.worker)
            if handle is not None and handle.alive:
                try:
                    handle.channel.send(Message("cancel", {
                        "flight_id": flight.flight_id,
                        "attempt": flight.attempt}))
                except ConnectionError:
                    pass
            self._replay_flight(flight, RECOVER_STALLED)

    def _replay_flight(self, flight: _DFlight, reason: str) -> None:
        """Recovery's one funnel: re-prefill-from-prompt. The replay
        prompt is `prompt + delivered_tokens` with the ORIGINAL sampling
        key — position-folded keys make the continuation byte-identical
        (see module docstring). Attempt bumps so stragglers of the old
        attempt are stale; attempt exhaustion sheds instead of looping."""
        now = self._clock()
        user = flight.user
        old_worker = flight.worker
        if user.trace_sampled:
            # the replay decision as a span: linked (not parented) to the
            # failed attempt's dispatch, tagged with the machine-readable
            # reason — the trace shows WHY the timeline restarts
            record_span(
                "serving.replay", flight.progress_at, now,
                trace=user.trace_id, parent=user.span_id,
                links=([flight.dispatch_span] if flight.dispatch_span
                       else None),
                recovery_reason=reason, attempt=flight.attempt,
                worker=old_worker)
        if reason in (RECOVER_STALLED, RECOVER_INSTALL_REFUSED,
                      RECOVER_WORKER_DROP):
            # loss reasons already noted in _lose_worker
            self._note_incident(reason, f"fleet-{reason}")
        self.recovery_log.append({
            "request_id": user.request_id,
            "flight_id": flight.flight_id,
            "attempt": flight.attempt,
            "recovery_reason": reason,
            "worker": old_worker,
        })
        if flight.phase == "pending":
            try:
                self._pending.remove(flight.flight_id)
            except ValueError:
                pass
        if flight.attempt >= self.pod_config.max_attempts:
            self._flights.pop(flight.flight_id, None)
            self._by_user.pop(id(user), None)
            user.status = RequestStatus.EXPIRED
            user.reject_reason = (
                f"gave up after {flight.attempt} attempts "
                f"(last: {reason} on worker {old_worker})")
            user.shed_code = SHED_WORKER_DROP
            user.retry_after_s = self.scheduler.retry_after_estimate()
            user.finished_at = now
            self.recovery_log.append({
                "request_id": user.request_id,
                "flight_id": flight.flight_id,
                "attempt": flight.attempt,
                "recovery_reason": RECOVER_GAVE_UP,
                "worker": old_worker,
            })
            self._finalize(user)
            return
        flight.attempt += 1
        flight.phase = "replay"
        flight.worker = -1
        flight.shipment = None
        flight.copied = 0
        flight.progress_at = now
        if flight.replay_started_at is None:
            flight.replay_started_at = now
        self._replay.append(flight.flight_id)
        self._c_replayed.inc()

    # -- assignment ----------------------------------------------------------

    def _role_pool(self, role: str) -> list[WorkerHandle]:
        """Alive, non-draining workers for a role. SOFT: if the role has
        no alive workers at all, every alive worker qualifies — a pod
        reduced to one survivor keeps serving both phases."""
        alive = [h for h in self.workers.values()
                 if h.alive and not h.draining]
        preferred = [h for h in alive if h.role == role]
        return preferred if preferred else alive

    def _worker_load(self, wid: int) -> int:
        return sum(1 for f in self._flights.values() if f.worker == wid)

    def _pick_worker(self, role: str) -> WorkerHandle | None:
        best, best_cap = None, 0
        for h in self._role_pool(role):
            cap = h.slots - self._worker_load(h.worker_id)
            if cap > best_cap:
                best, best_cap = h, cap
        return best

    def _assign_prefill(self) -> bool:
        """Replay queue first (recovery outranks fresh admissions — the
        user already has a live stream), then the front queue in policy
        order. Stops at the pending-shipment bound: same backpressure
        valve as PR 9."""
        worked = False
        now = self._clock()
        while True:
            if len(self._pending) >= self._max_pending:
                break
            handle = self._pick_worker("prefill")
            if handle is None:
                break
            flight: _DFlight | None = None
            if self._replay:
                flight = self._flights.get(self._replay[0])
                if flight is None:        # cancelled while queued
                    self._replay.popleft()
                    continue
            if flight is None:
                name = self.scheduler._select_tenant()
                if name is None:
                    break
                user = self.scheduler._pop_selected(name)
                user.status = RequestStatus.RUNNING
                user.admitted_at = now
                if user.trace_sampled:
                    record_span("serving.queue_wait", user.submitted_at,
                                now, trace=user.trace_id,
                                parent=user.span_id, tenant=user.tenant)
                key_raw = _as_raw_key(user.key)
                if key_raw is None:
                    # the single engine's derivation, verbatim — and
                    # derived ONCE, router-side, so every replay of this
                    # request reuses the same key (exactness under
                    # recovery depends on it)
                    import jax

                    key_raw = jax.random.key_data(
                        jax.random.fold_in(self._base_key, user.request_id))
                flight = _DFlight(
                    user=user, flight_id=self._next_flight_id,
                    key_raw=np.asarray(key_raw, np.uint32),
                    progress_at=now)
                self._next_flight_id += 1
                self._flights[flight.flight_id] = flight
                self._by_user[id(user)] = flight
            else:
                self._replay.popleft()
            user = flight.user
            # replay prompt = original prompt + every delivered token:
            # its "first token" samples at position prompt_len + d,
            # which IS token d of the original stream
            if user.tokens:
                prompt = np.concatenate(
                    [user.prompt, np.asarray(user.tokens, np.int32)])
            else:
                prompt = user.prompt
            # budget 2 keeps the worker's internal RUNNING past its first
            # token so pages are still mapped at extract — unless the
            # prompt is one short of max_len (PR 9's rule, re-applied to
            # the REPLAY length)
            budget = 2 if len(prompt) + 2 <= self.engine_config.max_len \
                else 1
            try:
                handle.channel.send(Message(
                    "submit",
                    {"flight_id": flight.flight_id,
                     "attempt": flight.attempt,
                     "budget": budget,
                     "temperature": user.temperature,
                     **trace_meta(user.trace_id, user.span_id or 0,
                                  user.trace_sampled)},
                    buffers=[np.asarray(prompt, np.int32), flight.key_raw]))
            except ConnectionError:
                self._lose_worker(handle, RECOVER_CHANNEL_DROP)
                # _lose_worker did NOT see this flight (worker still -1);
                # park it for the next pick
                flight.phase = "replay"
                self._replay.appendleft(flight.flight_id)
                continue
            flight.phase = "prefill"
            flight.worker = handle.worker_id
            flight.progress_at = now
            if user.trace_sampled:
                # instant marker; a later replay links back to it to say
                # WHICH attempt it supersedes
                flight.dispatch_span = record_span(
                    "serving.pod.dispatch", now, now,
                    trace=user.trace_id, parent=user.span_id,
                    flight_id=flight.flight_id, attempt=flight.attempt,
                    worker=handle.worker_id)
            worked = True
        return worked

    def _forward_pending(self) -> bool:
        """Land pending shipments on decode workers, strictly FIFO (no
        skip-ahead, PR 9's rule). The bounded channel send queue is the
        transport half of backpressure; this loop's stall counter is the
        router half — at most one increment per step."""
        worked = False
        while self._pending:
            flight = self._flights.get(self._pending[0])
            if flight is None or flight.user.done:
                self._pending.popleft()
                continue
            handle = self._pick_worker("decode")
            if handle is None:
                self._c_stalls.inc()
                break
            shipment = flight.shipment
            try:
                handle.channel.send(shipment_to_message(
                    shipment, flight_id=flight.flight_id,
                    attempt=flight.attempt,
                    **trace_meta(flight.user.trace_id,
                                 flight.user.span_id or 0,
                                 flight.user.trace_sampled)))
            except ConnectionError:
                self._lose_worker(handle, RECOVER_CHANNEL_DROP)
                continue       # head flight intact: try another worker
            self._pending.popleft()
            flight.phase = "decode"
            flight.worker = handle.worker_id
            flight.copied = 1          # the first token is already out
            flight.progress_at = self._clock()
            flight.shipment = None     # freed at send: router memory is
            #                            bounded; a lost shipment replays
            self._c_shipments.inc()
            self._c_pages_shipped.inc(shipment.n_prompt_pages)
            if flight.user.trace_sampled:
                # extracted_at was stamped on the PREFILL worker's clock:
                # rebase it into router time so the transfer span doesn't
                # float against the rest of the timeline
                src = self.workers.get(shipment.src_worker)
                offset = (src.clock_offset_s or 0.0) if src else 0.0
                start = shipment.extracted_at + offset
                record_span(
                    "serving.page_transfer", min(start, flight.progress_at),
                    flight.progress_at, trace=flight.user.trace_id,
                    parent=flight.user.span_id,
                    attempt=flight.attempt,
                    pages=shipment.n_prompt_pages,
                    bytes=shipment.page_bytes,
                    src_worker=shipment.src_worker,
                    dst_worker=handle.worker_id)
            worked = True
        return worked

    # -- elastic rebalancing -------------------------------------------------

    def _rebalance(self) -> None:
        """Convert ONE idle worker between roles per window, from live
        signals. Hysteresis: decode occupancy must cross `occupancy_high`
        to pull a prefill worker over, drop under `occupancy_low` to give
        one back — the band between is a dead zone, so the pod cannot
        flap. Never drops a role below one worker, never converts a
        worker that holds flights."""
        pc = self.pod_config
        if not pc.rebalance:
            return
        now = self._clock()
        if now - self._last_rebalance < pc.rebalance_window_s:
            return
        alive = [h for h in self.workers.values()
                 if h.alive and not h.draining]
        pref = [h for h in alive if h.role == "prefill"]
        dec = [h for h in alive if h.role == "decode"]
        if not pref or not dec:
            return      # soft-role survival mode; nothing to convert
        prefill_demand = self.scheduler.queue_depth + len(self._replay)
        decode_live = sum(1 for f in self._flights.values()
                          if f.phase == "decode")
        decode_occ = decode_live / max(1, sum(h.slots for h in dec))
        idle = [h for h in alive if self._worker_load(h.worker_id) == 0]
        target = None
        if ((decode_occ >= pc.occupancy_high
             or len(self._pending) >= self._max_pending)
                and prefill_demand == 0 and len(pref) > 1):
            cands = [h for h in idle if h.role == "prefill"]
            if cands:
                target, new_role = cands[0], "decode"
        elif (prefill_demand > 0 and decode_occ <= pc.occupancy_low
                and len(dec) > 1):
            cands = [h for h in idle if h.role == "decode"]
            if cands:
                target, new_role = cands[0], "prefill"
        if target is None:
            return
        direction = f"{target.role}_to_{new_role}"
        target.role = new_role
        self._c_conversions[direction].inc()
        self._last_rebalance = now
        try:
            target.channel.send(Message("set_role", {"role": new_role}))
        except ConnectionError:
            pass

    # -- terminal ------------------------------------------------------------

    def _finalize(self, req: Request) -> None:
        end = req.finished_at
        if end is None:
            end = self._clock()
        close_request_trace(req, end)
        self.metrics.observe_request(req)

    # -- metrics / observability ---------------------------------------------

    def _update_gauges(self) -> None:
        self._g_pending.set(len(self._pending))
        self._g_alive.set(sum(1 for h in self.workers.values() if h.alive))
        for role in ("prefill", "decode"):
            workers = [h for h in self.workers.values()
                       if h.alive and h.role == role]
            cap = sum(h.slots for h in workers)
            live = sum(self._worker_load(h.worker_id) for h in workers)
            self._g_occupancy[role].set(live / max(1, cap))

    def compile_stats(self) -> dict[str, int]:
        """Per-program compile counts as reported by worker heartbeats,
        aggregated as the MAX per program across workers — flat per
        program is still the pod's recompile guard."""
        out = {"admit": 0, "prefill": 0, "decode": 0, "extract": 0,
               "install": 0}
        for h in self.workers.values():
            for k, v in (h.compiles or {}).items():
                out[k] = max(out.get(k, 0), int(v))
        return out

    def metrics_summary(self) -> dict[str, float]:
        out = self.metrics.summary()
        out.update({f"compiles_{k}": float(v)
                    for k, v in self.compile_stats().items()})
        out["pod_shipments"] = float(self._c_shipments.value)
        out["pod_pages_shipped"] = float(self._c_pages_shipped.value)
        out["pod_backpressure_stalls"] = float(self._c_stalls.value)
        out["pod_workers_lost"] = float(self._c_lost.value)
        out["pod_workers_recovered"] = float(self._c_recovered.value)
        out["pod_requests_replayed"] = float(self._c_replayed.value)
        out["pod_stale_messages"] = float(self._c_stale.value)
        out["pod_role_conversions"] = float(sum(
            c.value for c in self._c_conversions.values()))
        if self._h_recovery.count:
            out["pod_recovery_latency_p50_ms"] = \
                self._h_recovery.quantile(0.5) * 1e3
            out["pod_recovery_latency_p99_ms"] = \
                self._h_recovery.quantile(0.99) * 1e3
            out["pod_recovery_latency_mean_ms"] = self._h_recovery.mean * 1e3
        out["pod_spans_ingested"] = float(self._c_spans.value)
        now = self._clock()
        lags = [now - h.last_span_at for h in self.workers.values()
                if h.last_span_at is not None]
        if lags:
            # the SLOWEST exporter bounds how fresh a merged trace is
            out["pod_span_export_lag_s"] = max(lags)
        return out

    def exposition_registry(self) -> MetricsRegistry:
        """The router's `/metrics` view: its own series verbatim, plus
        every worker's last-heartbeat registry snapshot merged with the
        `aggregate_snapshot` semantics (counter sums, gauge min/mean/max,
        sketch-merged histograms incl. `__slowest_host_mean`) under
        `origin="workers"` — one scrape shows the whole pod, no jax
        process group involved.

        Staleness-honest: every contributing snapshot also exposes its
        age (`serving_pod_worker_snapshot_age_seconds{worker=}`), and a
        LOST worker's numbers merge under an extra `stale="true"` label —
        frozen counters from a dead process must not impersonate live
        ones. Past `snapshot_stale_after_s` (when set) they drop
        entirely."""
        reg = MetricsRegistry()
        for kind, name, labels, metric in self.registry.items():
            if kind == "counter":
                reg.counter(name, **dict(labels)).inc(metric.value)
            elif kind == "gauge":
                reg.gauge(name, **dict(labels)).set(metric.value)
            else:
                reg.histogram(name, **dict(labels)).merge(metric)
        now = self._clock()
        horizon = self.pod_config.snapshot_stale_after_s
        live, stale = [], []
        for h in self.workers.values():
            if h.snapshot is None:
                continue
            reg.gauge("serving_pod_worker_snapshot_age_seconds",
                      worker=str(h.worker_id)).set(
                          max(0.0, now - h.snapshot_at))
            if h.alive and not h.lost:
                live.append(h.snapshot)
            elif horizon is None or now - h.last_heartbeat <= horizon:
                stale.append(h.snapshot)
        if live:
            merged_registry(live, registry=reg, origin="workers")
        if stale:
            merged_registry(stale, registry=reg, origin="workers",
                            stale="true")
        return reg

    def reset_metrics(self) -> None:
        self.registry.reset()
        self.metrics = ServingMetrics(registry=self.registry)
        self.scheduler.step_time_ema = 0.0

    def close(self) -> None:
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        for handle in self.workers.values():
            try:
                handle.channel.send(Message("drain", {}))
            except ConnectionError:
                pass
            handle.channel.close()
        if self.listener is not None:
            self.listener.close()

    # -- introspection -------------------------------------------------------

    def debug_requests(self) -> dict:
        from ...engine import Engine

        now = self._clock()
        return {
            "queued": [Engine._request_info(r, now)
                       for r in self.scheduler.queue],
            "running": [dict(Engine._request_info(f.user, now),
                             phase=f.phase, attempt=f.attempt,
                             worker=f.worker)
                        for f in self._flights.values()],
        }

    def debug_pod(self) -> dict:
        phases: dict[str, int] = {}
        for f in self._flights.values():
            phases[f.phase] = phases.get(f.phase, 0) + 1
        now = self._clock()
        return {
            "workers": [{
                "worker_id": h.worker_id, "role": h.role,
                "alive": h.alive, "lost": h.lost, "draining": h.draining,
                "busy": h.busy, "pid": h.pid,
                "slots": h.slots,
                "load": self._worker_load(h.worker_id),
                "heartbeat_age_s": (now - h.last_heartbeat
                                    if h.last_heartbeat else None),
                "snapshot_age_s": (now - h.snapshot_at
                                   if h.snapshot is not None else None),
                "clock_offset_s": h.clock_offset_s,
                "clock_rtt_s": h.clock_rtt_s,
                "span_export_lag_s": (now - h.last_span_at
                                      if h.last_span_at is not None
                                      else None),
                "stats": h.stats, "compiles": h.compiles,
            } for h in self.workers.values()],
            "in_flight": phases,
            "queued": self.scheduler.queue_depth,
            "pending_shipments": len(self._pending),
            "replay_queue": len(self._replay),
            "max_pending_shipments": self._max_pending,
            "workers_lost_total": int(self._c_lost.value),
            "workers_recovered_total": int(self._c_recovered.value),
            "requests_replayed_total": int(self._c_replayed.value),
            "recovery_log": list(self.recovery_log)[-16:],
        }

    def debug_slots(self) -> list[dict]:
        # the router holds no slots; the /debug/slots route gets the
        # heartbeat-reported occupancy of every worker instead
        return [{
            "worker": h.worker_id, "role": h.role, "alive": h.alive,
            "slots": h.slots,
            "live_slots": (h.stats or {}).get("live_slots"),
            "flights": self._worker_load(h.worker_id),
        } for h in self.workers.values()]

    def debug_pages(self) -> dict:
        return {str(h.worker_id): {
            "role": h.role, "alive": h.alive,
            "pages_free": (h.stats or {}).get("pages_free"),
            "pages_in_use": (h.stats or {}).get("pages_in_use"),
        } for h in self.workers.values()}

    def debug_scheduler(self) -> dict:
        out = self.scheduler.debug_state()
        out["pod"] = {
            "in_flight": len(self._flights),
            "pending_shipments": len(self._pending),
            "replay_queue": len(self._replay),
        }
        return out

    def incident_dumps(self) -> dict:
        out: dict[str, Any] = {}
        for name, build in (
            ("pod", self.debug_pod),
            ("requests", self.debug_requests),
            ("scheduler", self.debug_scheduler),
            ("compile_stats", self.compile_stats),
            ("clock_offsets", self._clock_offsets),
            ("flights_trace", self._flights_trace),
        ):
            try:
                out[name] = build()
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- fleet incident bundles ----------------------------------------------

    def _clock_offsets(self) -> dict:
        now = self._clock()
        return {str(h.worker_id): {
            "role": h.role, "alive": h.alive, "lost": h.lost,
            "offset_s": h.clock_offset_s, "rtt_s": h.clock_rtt_s,
            "heartbeat_age_s": (now - h.last_heartbeat
                                if h.last_heartbeat else None),
        } for h in self.workers.values()}

    def _flights_trace(self) -> dict:
        """Merged chrome traces of every in-flight sampled request —
        worker spans are already rebased into router time at ingest, so
        each document is ONE aligned Perfetto timeline."""
        out: dict[str, Any] = {}
        for f in self._flights.values():
            tid = f.user.trace_id
            if tid is None or not f.user.trace_sampled:
                continue
            try:
                out[str(tid)] = export_chrome_trace(trace_id=tid)
            except Exception as e:
                out[str(tid)] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def _note_incident(self, reason: str, name: str | None = None) -> None:
        """Arm a fleet bundle for the END of this step. First trigger
        wins — a cascade (loss -> replays -> sanitizer) is one incident,
        not four bundles."""
        if self._incident_dir is None:
            return
        if self._pending_incident is None:
            self._pending_incident = (reason, name or f"fleet-{reason}")

    def _maybe_write_fleet_bundle(self) -> None:
        if self._pending_incident is None:
            return
        reason, name = self._pending_incident
        self._pending_incident = None
        # wall clock on purpose: rate-limits real disk writes even under
        # a fake injected clock, so a flake storm cannot DoS the disk
        now = time.monotonic()
        if (self._last_fleet_bundle is not None
                and now - self._last_fleet_bundle
                < self.pod_config.fleet_bundle_min_interval_s):
            return
        self._last_fleet_bundle = now
        try:
            self.write_fleet_incident_bundle(reason, name=name)
        except Exception:
            pass   # incident capture must never take down serving

    def fetch_worker_dumps(self, timeout_s: float | None = None) \
            -> dict[int, dict]:
        """`incident_dumps` from every reachable worker over a bounded
        RPC: fan out `incident_request`, pump replies off the normal
        dispatch path, give up per-worker at the deadline. Unreachable
        workers yield a `worker_error` stanza — a fleet bundle is always
        complete, just honest about holes."""
        budget = (self.pod_config.incident_rpc_timeout_s
                  if timeout_s is None else timeout_s)
        self._incident_seq += 1
        rid = self._incident_seq
        out: dict[int, dict] = {}
        asked: list[WorkerHandle] = []
        for handle in self.workers.values():
            if not handle.alive or handle.channel.closed:
                out[handle.worker_id] = {
                    "worker_error": "unreachable (lost)"}
                continue
            try:
                handle.channel.send(
                    Message("incident_request", {"req_id": rid}))
            except ConnectionError:
                out[handle.worker_id] = {
                    "worker_error": "unreachable (send failed)"}
                continue
            asked.append(handle)
        # wall-clock deadline: an injected fake clock doesn't tick while
        # we block here, and a dead worker must not hang the bundle
        deadline = time.monotonic() + budget
        while asked and time.monotonic() < deadline:
            for handle in asked:
                if handle.local is not None and not handle.lost:
                    handle.local.run_once()
            self._dispatch_inbound()
            for handle in list(asked):
                dumps = self._incident_replies.pop(
                    (rid, handle.worker_id), None)
                if dumps is not None:
                    out[handle.worker_id] = dumps
                    asked.remove(handle)
            if asked and not self._has_local_workers():
                # deliberate: incident capture is synchronous by design —
                # the pod is already broken, and the wait is bounded by
                # `budget` above
                time.sleep(0.005)  # atp: disable=ATP303
        for handle in asked:
            out[handle.worker_id] = {
                "worker_error": f"no reply within {budget}s"}
        return out

    def write_fleet_incident_bundle(self, reason: str,
                                    name: str | None = None) -> str | None:
        """ONE bundle for a pod-wide event: the router's own dumps, every
        reachable worker's `incident_dumps` (`worker_<id>` sections),
        clock offsets, and the merged chrome trace of each in-flight
        request. Returns the bundle path (None when no incident dir)."""
        if self._incident_dir is None:
            return None
        worker_dumps = self.fetch_worker_dumps()
        dumps: dict[str, Any] = self.incident_dumps()
        for wid, wd in sorted(worker_dumps.items()):
            dumps[f"worker_{wid}"] = wd
        report = {
            "kind": "fleet_incident",
            "reason": reason,
            "workers": sorted(self.workers),
            "clock_offsets": dumps.get("clock_offsets"),
            "recovery_log": list(self.recovery_log)[-32:],
        }
        return write_incident_bundle(
            self._incident_dir, report,
            registry=self.exposition_registry(), dumps=dumps,
            name=name or f"fleet-{reason}")


# ---------------------------------------------------------------------------
# in-process factory (the deterministic `local` distributed form)
# ---------------------------------------------------------------------------


def build_local_distributed_pod(
    family, config, params,
    engine_config: EngineConfig | None = None,
    pod_config: DistributedPodConfig | None = None,
    clock=time.monotonic,
    channel_wrap=None,
):
    """Router + in-process `WorkerServer`s over `LocalChannel` pairs —
    every message still crosses the wire codec, the clock can be fake,
    and the router pumps the workers itself, so the whole distributed
    protocol (heartbeats, recovery, rebalancing) runs deterministically
    in one interpreter. `channel_wrap(worker_id, role, channel)` may
    wrap the ROUTER-side endpoint (e.g. with `FlakyTransport`).

    Returns (router, workers)."""
    from ...engine import Engine
    from .transport import LocalChannel

    ec = engine_config or EngineConfig()
    pc = pod_config or DistributedPodConfig()
    worker_ec = dataclasses.replace(
        ec, tenants=None, metrics_port=None, watchdog_timeout_s=None,
        incident_dir=None, speculative=None)
    router = DistributedPodRouter(
        engine_config=ec, pod_config=pc, clock=clock)
    workers = []
    wid = 0
    for role, count in (("prefill", pc.prefill_workers),
                        ("decode", pc.decode_workers)):
        for _ in range(count):
            router_side, worker_side = LocalChannel.pair()
            if channel_wrap is not None:
                router_side = channel_wrap(wid, role, router_side)
            engine = Engine(family, config, params, worker_ec, clock=clock)
            engine.close()   # heartbeats are the worker's only exporter
            server = WorkerServer(
                engine, worker_side, worker_id=wid, role=role,
                heartbeat_interval_s=pc.heartbeat_interval_s, clock=clock,
                # in-process workers share the router's span ring —
                # exporting over the wire would double every span
                export_spans=False)
            router.register_worker(router_side, wid, role,
                                   slots=len(engine.scheduler.slots),
                                   local=server)
            workers.append(server)
            wid += 1
    return router, workers
