"""The pod's wire format: length-prefixed frames, no pickle on the hot path.

A multi-host pod moves two very different kinds of traffic:

- **control** — submits, cancels, heartbeats, role changes, token
  deltas. Small, structured, JSON-shaped.
- **KV page shipments** — the hot path. A prompt's prefilled pages are
  megabytes of fixed-shape tensor data (int8 codes + scales already
  halved the bytes — PR 10); serializing them through pickle would copy,
  tag, and re-validate every buffer per hop.

One frame format carries both: a JSON header (kind + JSON-safe metadata
+ buffer descriptors) followed by the raw buffer bytes back-to-back.
Numpy arrays cross the wire as their contiguous bytes plus a
(dtype, shape) descriptor in the header — decode is a zero-copy
`np.frombuffer` view per buffer. Nothing on either path executes
arbitrary code: a corrupt or malicious frame can fail to parse, never
`__reduce__` its way into the interpreter.

Frame layout (all integers big-endian)::

    [4B magic b"ATPD"] [4B header_len H] [8B body_len B]
    [H bytes: UTF-8 JSON header] [B bytes: buffer payloads]

    header = {"kind": str, "meta": {...}, "buffers": [
        {"dtype": "<f4", "shape": [2, 3]}, ...]}

`MAX_FRAME_BYTES` bounds what a reader will allocate for one frame —
a garbage length prefix must not OOM the router.

Trace context rides the header, not the framing: any job-bearing
message's meta may carry an optional ``traceparent`` (the W3C
`00-<trace>-<span>-<flags>` string) plus a ``sampled`` verdict —
`trace_meta` builds the pair, and a missing/malformed header simply
means "unsampled" (never an error; tracing must not be able to break
the dataflow). The router stamps it on submits and shipments so a
worker's engine spans join the router-minted request trace.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Any

import numpy as np

__all__ = [
    "Message",
    "encode_message",
    "decode_message",
    "read_frame",
    "write_frame",
    "shipment_to_message",
    "shipment_from_message",
    "trace_meta",
    "WireError",
]

MAGIC = b"ATPD"
_HEAD = struct.Struct(">4sIQ")  # magic, header_len, body_len
MAX_FRAME_BYTES = 1 << 31  # 2 GiB: far above any shipment, far below garbage


class WireError(ValueError):
    """A frame that cannot be (or must not be) decoded."""


@dataclasses.dataclass
class Message:
    """One decoded frame: a kind tag, JSON-safe metadata, raw buffers."""

    kind: str
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)
    buffers: list[np.ndarray] = dataclasses.field(default_factory=list)


def encode_message(msg: Message) -> bytes:
    """Message -> one self-delimiting frame (bytes)."""
    descs = []
    payloads = []
    for buf in msg.buffers:
        arr = np.ascontiguousarray(buf)
        # extension dtypes (bfloat16 via ml_dtypes) stringify as opaque
        # void ("<V2") — ship the registered name, which np.dtype resolves
        tag = arr.dtype.str
        if np.dtype(tag) != arr.dtype:
            tag = arr.dtype.name
        descs.append({"dtype": tag, "shape": list(arr.shape)})
        payloads.append(arr.tobytes())
    header = json.dumps(
        {"kind": msg.kind, "meta": msg.meta, "buffers": descs},
        separators=(",", ":")).encode("utf-8")
    body_len = sum(len(p) for p in payloads)
    return b"".join([_HEAD.pack(MAGIC, len(header), body_len), header,
                     *payloads])


def decode_message(frame: bytes) -> Message:
    """One frame (as produced by `encode_message`) -> Message. Raises
    `WireError` on any structural problem — truncation, bad magic,
    length/descriptor disagreement."""
    if len(frame) < _HEAD.size:
        raise WireError(f"frame too short ({len(frame)} bytes)")
    magic, header_len, body_len = _HEAD.unpack_from(frame)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if len(frame) != _HEAD.size + header_len + body_len:
        raise WireError(
            f"frame length {len(frame)} != header {_HEAD.size + header_len} "
            f"+ body {body_len}")
    try:
        header = json.loads(
            frame[_HEAD.size:_HEAD.size + header_len].decode("utf-8"))
        kind, meta = header["kind"], header["meta"]
        descs = header["buffers"]
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise WireError(f"bad frame header: {e}") from None
    buffers = []
    offset = _HEAD.size + header_len
    for d in descs:
        try:
            dtype = np.dtype(d["dtype"])
            shape = tuple(int(s) for s in d["shape"])
        except (TypeError, KeyError, ValueError) as e:
            raise WireError(f"bad buffer descriptor {d!r}: {e}") from None
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if offset + nbytes > len(frame):
            raise WireError("buffer descriptors overrun the frame body")
        count = int(np.prod(shape, dtype=np.int64))
        arr = np.frombuffer(frame, dtype=dtype, count=count, offset=offset)
        buffers.append(arr.reshape(shape))
        offset += nbytes
    if offset != len(frame):
        raise WireError("frame body longer than its buffer descriptors")
    return Message(kind=kind, meta=meta, buffers=buffers)


# ---------------------------------------------------------------------------
# socket framing
# ---------------------------------------------------------------------------


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError (EOF mid-frame is a
    dropped peer, not a short read)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> bytes:
    """Read one complete frame off a blocking socket. Raises
    ConnectionError on EOF, WireError on a garbage prefix."""
    head = _recv_exact(sock, _HEAD.size)
    magic, header_len, body_len = _HEAD.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} on stream")
    total = header_len + body_len
    if total > MAX_FRAME_BYTES:
        raise WireError(f"frame claims {total} bytes (> MAX_FRAME_BYTES)")
    return head + _recv_exact(sock, total)


def write_frame(sock, frame: bytes) -> None:
    sock.sendall(frame)


# ---------------------------------------------------------------------------
# KVPageShipment <-> Message
# ---------------------------------------------------------------------------

# buffer order is part of the wire contract (header carries no names)
_SHIP_BUFFERS = ("prompt", "k_pages", "v_pages", "key_raw")


def trace_meta(trace_id, span_id: int = 0, sampled: bool = False) -> dict:
    """Meta fields that propagate one request's trace context across a
    hop: a W3C ``traceparent`` plus the router's head-sampling verdict.
    Empty dict when the request has no trace id (tracing off) — the
    caller splices it into any message meta with ``**``, so untraced
    traffic carries zero extra bytes."""
    if not trace_id:
        return {}
    from ....telemetry.trace import format_traceparent

    return {
        "traceparent": format_traceparent(str(trace_id), span_id or 0,
                                          bool(sampled)),
        "sampled": bool(sampled),
    }


def shipment_to_message(shipment, **extra_meta) -> Message:
    """The existing fixed-shape codes+scales shipment as one frame:
    scalars ride the header, tensors ride as raw buffers (int8 pools ship
    their codes + per-row scale blocks verbatim — the wire carries half a
    bf16 shipment's bytes, exactly as in-process transfer does)."""
    meta = {
        "first_token": int(shipment.first_token),
        "n_prompt_pages": int(shipment.n_prompt_pages),
        "temperature": float(shipment.temperature),
        "max_new_tokens": int(shipment.max_new_tokens),
        "eos_token_id": (None if shipment.eos_token_id is None
                         else int(shipment.eos_token_id)),
        "src_worker": int(shipment.src_worker),
        "extracted_at": float(shipment.extracted_at),
        "first_logprob": (None if shipment.first_logprob is None
                          else float(shipment.first_logprob)),
        "quantized": shipment.k_scales is not None,
    }
    meta.update(extra_meta)
    buffers = [np.asarray(getattr(shipment, name)) for name in _SHIP_BUFFERS]
    if shipment.k_scales is not None:
        buffers += [np.asarray(shipment.k_scales),
                    np.asarray(shipment.v_scales)]
    return Message(kind="shipment", meta=meta, buffers=buffers)


def shipment_from_message(msg: Message):
    """Inverse of `shipment_to_message` (byte-identical round trip —
    pinned by test)."""
    from ..transfer import KVPageShipment

    meta = msg.meta
    want = len(_SHIP_BUFFERS) + (2 if meta.get("quantized") else 0)
    if len(msg.buffers) != want:
        raise WireError(
            f"shipment frame has {len(msg.buffers)} buffers, wants {want}")
    prompt, k_pages, v_pages, key_raw = msg.buffers[:4]
    k_scales = v_scales = None
    if meta.get("quantized"):
        k_scales, v_scales = msg.buffers[4:6]
    return KVPageShipment(
        prompt=np.asarray(prompt, np.int32),
        first_token=int(meta["first_token"]),
        n_prompt_pages=int(meta["n_prompt_pages"]),
        k_pages=k_pages,
        v_pages=v_pages,
        key_raw=np.asarray(key_raw, np.uint32),
        temperature=float(meta["temperature"]),
        max_new_tokens=int(meta["max_new_tokens"]),
        eos_token_id=(None if meta["eos_token_id"] is None
                      else int(meta["eos_token_id"])),
        src_worker=int(meta.get("src_worker", -1)),
        extracted_at=float(meta.get("extracted_at", 0.0)),
        first_logprob=(None if meta.get("first_logprob") is None
                       else float(meta["first_logprob"])),
        k_scales=k_scales,
        v_scales=v_scales,
    )
