"""Channels: how router and workers talk, local or across processes.

Three implementations of one tiny contract (`send` / `poll` / `closed`):

- `LocalChannel` — an in-process pair of deques that still pushes every
  message through the wire codec (encode on send, decode on poll), so
  the deterministic in-process pod tests exercise the exact bytes the
  socket path ships. This is what keeps the `local` transport honest:
  if a field can't survive the frame format, the PR 9–16 suites see it.
- `SocketChannel` — one TCP connection, a reader thread decoding frames
  into an inbox, and a writer thread draining a **bounded** send queue.
  The bound is the backpressure story: when a decode worker can't
  absorb shipments, `send` blocks the *router's* forwarding step (which
  already counts the stall); prefill workers keep extracting because
  nothing upstream of the router ever waits on a full queue.
- `FlakyTransport` — a deterministic fault injector wrapping any
  channel: drop / duplicate / delay / reorder individual messages, or
  kill / hang the link entirely. Plans are scripted or seeded so every
  recovery test replays identically.

Poll is non-blocking everywhere; the router's step loop owns pacing.
"""

from __future__ import annotations

import collections
import queue
import random
import socket
import threading
from typing import Callable, Iterable

from ....telemetry.lockwatch import maybe_tracked
from .wire import (Message, decode_message, encode_message, read_frame,
                   write_frame)

__all__ = [
    "Channel",
    "LocalChannel",
    "SocketChannel",
    "ChannelListener",
    "FlakyTransport",
    "DEFAULT_SEND_QUEUE_DEPTH",
]

# Enough for a heartbeat + a couple of shipments in flight; small enough
# that a stuck worker stalls the router within one window of traffic.
DEFAULT_SEND_QUEUE_DEPTH = 8


class Channel:
    """Bidirectional, ordered (per direction), message-oriented link."""

    def send(self, msg: Message) -> None:
        raise NotImplementedError

    def poll(self) -> list[Message]:
        """All messages that have arrived; never blocks."""
        raise NotImplementedError

    @property
    def closed(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class LocalChannel(Channel):
    """One endpoint of an in-process pair. Every message round-trips
    through the frame codec so in-process tests pin wire fidelity."""

    def __init__(self) -> None:
        self._inbox: collections.deque[bytes] = collections.deque()
        self._peer: "LocalChannel | None" = None
        self._closed = False
        self.bytes_sent = 0
        self.bytes_received = 0

    @classmethod
    def pair(cls) -> tuple["LocalChannel", "LocalChannel"]:
        a, b = cls(), cls()
        a._peer, b._peer = b, a
        return a, b

    def send(self, msg: Message) -> None:
        if self._closed or self._peer is None or self._peer._closed:
            raise ConnectionError("local channel closed")
        frame = encode_message(msg)
        self.bytes_sent += len(frame)
        self._peer._inbox.append(frame)

    def poll(self) -> list[Message]:
        out = []
        while self._inbox:
            frame = self._inbox.popleft()
            self.bytes_received += len(frame)
            out.append(decode_message(frame))
        return out

    @property
    def closed(self) -> bool:
        return self._closed or (self._peer is not None and self._peer._closed)

    def close(self) -> None:
        self._closed = True


class SocketChannel(Channel):
    """One TCP connection with a bounded send queue.

    `send` blocks when the queue is full (that IS the backpressure), and
    raises ConnectionError once the link is dead so callers fail fast
    instead of queueing into the void. Any socket error in either
    thread marks the channel closed; the owner notices via `.closed`
    and runs its recovery path — no exception escapes a daemon thread.
    """

    def __init__(self, sock: socket.socket,
                 send_queue_depth: int = DEFAULT_SEND_QUEUE_DEPTH) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._sendq: queue.Queue[bytes | None] = queue.Queue(
            maxsize=max(1, send_queue_depth))
        self._inbox: collections.deque[Message] = collections.deque()
        self._lock = maybe_tracked("pod-channel")
        self._closed = threading.Event()
        self.bytes_sent = 0
        self.bytes_received = 0
        self._reader = threading.Thread(
            target=self._read_loop, name="atp-pod-reader", daemon=True)
        self._writer = threading.Thread(
            target=self._write_loop, name="atp-pod-writer", daemon=True)
        self._reader.start()
        self._writer.start()

    @classmethod
    def connect(cls, host: str, port: int, timeout_s: float = 30.0,
                **kwargs) -> "SocketChannel":
        sock = socket.create_connection((host, port), timeout=timeout_s)
        sock.settimeout(None)
        return cls(sock, **kwargs)

    def _read_loop(self) -> None:
        try:
            while not self._closed.is_set():
                frame = read_frame(self._sock)
                msg = decode_message(frame)
                with self._lock:
                    self.bytes_received += len(frame)
                    self._inbox.append(msg)
        except Exception:
            self._mark_closed()

    def _write_loop(self) -> None:
        try:
            while True:
                frame = self._sendq.get()
                if frame is None:
                    return
                write_frame(self._sock, frame)
                with self._lock:
                    self.bytes_sent += len(frame)
        except Exception:
            self._mark_closed()

    def _mark_closed(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock any sender parked on a full queue
        try:
            self._sendq.put_nowait(None)
        except queue.Full:
            pass

    def send(self, msg: Message) -> None:
        frame = encode_message(msg)
        while True:
            if self._closed.is_set():
                raise ConnectionError("socket channel closed")
            try:
                self._sendq.put(frame, timeout=0.1)
                return
            except queue.Full:
                continue  # bounded queue full: block the caller (router)

    def poll(self) -> list[Message]:
        out = []
        with self._lock:
            while self._inbox:
                out.append(self._inbox.popleft())
        return out

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def close(self) -> None:
        self._mark_closed()
        # reap the IO threads: the socket shutdown kicks the reader out
        # of read_frame and the None sentinel kicks the writer off the
        # queue, so both exit promptly. `_mark_closed` itself must NOT
        # join — it runs on the reader/writer's own error paths, and a
        # thread cannot join itself.
        me = threading.current_thread()
        if self._reader is not me:
            self._reader.join(timeout=5.0)
        if self._writer is not me:
            self._writer.join(timeout=5.0)


class ChannelListener:
    """Router-side accept socket: workers dial in, the router polls
    `accept_all()` each step for new channels (non-blocking)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 send_queue_depth: int = DEFAULT_SEND_QUEUE_DEPTH) -> None:
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self._srv.setblocking(False)
        self._depth = send_queue_depth
        self.host, self.port = self._srv.getsockname()[:2]

    def accept_all(self) -> list[SocketChannel]:
        out = []
        while True:
            try:
                sock, _addr = self._srv.accept()
            except (BlockingIOError, OSError):
                return out
            sock.setblocking(True)
            out.append(SocketChannel(sock, send_queue_depth=self._depth))

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

_ACTIONS = ("ok", "drop", "dup", "delay", "reorder")


class FlakyTransport(Channel):
    """Deterministic fault injector around any channel.

    Each message (per direction, sequence-numbered) is assigned one of:

    - ``ok``      — pass through
    - ``drop``    — silently discarded (lost datagram / dead hop)
    - ``dup``     — delivered twice (retransmit race)
    - ``delay``   — held for ``delay_ticks`` calls of the moving side
    - ``reorder`` — held until the *next* message passes, then delivered

    The plan is either an explicit ``rules(direction, kind, seq)``
    callable (direction is ``"send"`` or ``"recv"``) or a seeded RNG via
    ``flake_rate`` — both replay identically run to run. Beyond message
    faults, ``kill()`` closes the link (dropped-connection recovery) and
    ``hang()`` keeps it open but silent both ways (the missed-heartbeat
    path: the worker looks alive at the TCP layer and says nothing).
    """

    def __init__(self, inner: Channel,
                 rules: Callable[[str, str, int], str] | None = None,
                 flake_rate: float = 0.0, seed: int = 0,
                 delay_ticks: int = 2,
                 protect_kinds: Iterable[str] = ()) -> None:
        self.inner = inner
        self._rules = rules
        self._rng = random.Random(seed)
        self._flake_rate = flake_rate
        self._delay_ticks = delay_ticks
        self._protect = frozenset(protect_kinds)
        self._seq = {"send": 0, "recv": 0}
        self._held: dict[str, list[list]] = {"send": [], "recv": []}
        self._hung = False
        self.faults: collections.Counter[str] = collections.Counter()

    def _action(self, direction: str, kind: str) -> str:
        seq = self._seq[direction]
        self._seq[direction] = seq + 1
        if kind in self._protect:
            return "ok"
        if self._rules is not None:
            action = self._rules(direction, kind, seq)
            if action not in _ACTIONS:
                raise ValueError(f"unknown fault action {action!r}")
        elif self._flake_rate and self._rng.random() < self._flake_rate:
            action = self._rng.choice(("drop", "dup", "delay", "reorder"))
        else:
            action = "ok"
        if action != "ok":
            self.faults[f"{direction}:{action}"] += 1
        return action

    def _tick_held(self, direction: str, deliver) -> None:
        kept = []
        for entry in self._held[direction]:
            mode, msg, ticks = entry
            if mode == "delay":
                ticks -= 1
                if ticks <= 0:
                    deliver(msg)
                else:
                    kept.append([mode, msg, ticks])
            else:
                kept.append(entry)
        self._held[direction] = kept

    def _release_reorders(self, direction: str, deliver) -> None:
        kept = []
        for entry in self._held[direction]:
            if entry[0] == "reorder":
                deliver(entry[1])
            else:
                kept.append(entry)
        self._held[direction] = kept

    def _route(self, direction: str, msg: Message, deliver) -> None:
        action = self._action(direction, msg.kind)
        if action == "drop":
            return
        if action == "dup":
            deliver(msg)
            deliver(msg)
            return
        if action == "delay":
            self._held[direction].append(["delay", msg, self._delay_ticks])
            return
        if action == "reorder":
            self._held[direction].append(["reorder", msg, 0])
            return
        deliver(msg)
        # a message got through: anything held for reordering now follows it
        self._release_reorders(direction, deliver)

    def send(self, msg: Message) -> None:
        if self.inner.closed:
            # kill beats hang: a dead link fails fast even while wedged
            raise ConnectionError("flaky transport: link closed")
        if self._hung:
            return  # swallowed: the link looks open, nothing moves
        self._tick_held("send", self.inner.send)
        self._route("send", msg, self.inner.send)

    def poll(self) -> list[Message]:
        if self._hung:
            self.inner.poll()  # drain so a later un-hang can't replay
            return []
        out: list[Message] = []
        self._tick_held("recv", out.append)
        for msg in self.inner.poll():
            self._route("recv", msg, out.append)
        return out

    def kill(self) -> None:
        """Hard-drop the link: `.closed` flips, sends raise."""
        self.faults["kill"] += 1
        self.inner.close()

    def hang(self) -> None:
        """Wedge the link silently: open at the transport layer, but no
        message moves in either direction (missed-heartbeat recovery)."""
        self.faults["hang"] += 1
        self._hung = True

    @property
    def closed(self) -> bool:
        return self.inner.closed

    def close(self) -> None:
        self.inner.close()
