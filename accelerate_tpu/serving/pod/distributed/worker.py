"""Pod worker: one Engine behind a channel, role assigned by the router.

A worker is deliberately role-AGNOSTIC: it owns a single `Engine` plus
its `PageTransport` and executes whatever the router sends — `submit`
messages (prefill a prompt, ship its pages back) or `shipment` messages
(land the pages, decode to completion, stream token state back). "Role"
is a *label* the router uses for placement preference and elastic
rebalancing; converting a worker between prefill and decode is a
router-side bookkeeping flip plus a `set_role` notice, never a process
restart. That is also what makes single-survivor recovery possible: if
every decode worker dies, the remaining prefill worker simply starts
receiving shipments.

Token delivery is FULL-STATE sync, not deltas: every `tokens` message
carries the internal request's complete token/logprob lists. Resending
the whole (small — bounded by max_new_tokens) list makes delivery
idempotent and monotone, so dropped, duplicated, or reordered messages
need no acks and no sequence recovery — the router just keeps the
longest prefix it has seen for the flight's current attempt. A
production transport would delta-encode with acks; the exactness and
recovery semantics are identical.

Every job-bearing message carries ``(flight_id, attempt)`` and every
reply echoes it. The router bumps `attempt` on each replay, so a
duplicate or late message from an earlier attempt is recognizably stale
and dropped on both sides — this is what makes at-least-once delivery
safe under re-prefill recovery (no token delivered twice).

`run_once()` is one deterministic pump (poll, dispatch, step, harvest,
sync, heartbeat) — the in-process tests drive it directly under a fake
clock. `run()` wraps it in the real loop with SIGTERM drain mirroring
`serve`: finish in-flight work, say `bye`, exit.

Observability (ISSUE 18) crosses the boundary in both directions:

- inbound `traceparent` meta (submits AND shipments) joins this
  worker's engine spans to the router-minted request trace, so a
  prefill on worker A and the decode on worker B belong to ONE trace;
- heartbeats export the worker's recent ring-buffer span events
  (bounded, newest-first — `telemetry.trace.drain_spans`) plus the
  NTP-style echo (`ack`) the router needs to estimate this worker's
  clock offset and rebase those spans into router time;
- a `busy` heartbeat announces "entering a device block that may
  outlast the heartbeat interval" (first-compile, long steps) BEFORE
  going silent, so the router can defer the phantom `heartbeat_timeout`
  verdict — the documented PR 17 hazard;
- an `incident_request` message answers with this worker's
  `incident_dumps()` so the router's fleet incident bundle freezes
  every process's state, not just its own.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import numpy as np

from ....telemetry.trace import (
    drain_spans,
    parse_traceparent,
    record_span,
    tracing_enabled,
)
from ...scheduler import RequestStatus
from ..transfer import PageTransport, place_shipment
from .transport import Channel
from .wire import Message, shipment_from_message, shipment_to_message

__all__ = ["WorkerServer", "build_worker_engine", "engine_config_from_spec",
           "ENGINE_SPEC_KEYS"]

# the engine-spec dict shared by CLI workers / tests / serve_bench so
# separate processes build byte-identical engines (family + seed pin
# the params; the rest pins the compiled-shape envelope)
ENGINE_SPEC_KEYS = ("family", "seed", "num_slots", "max_len",
                    "prefill_chunk", "page_size", "max_queue",
                    "cache_dtype", "kv_dtype", "prefix_cache")


def build_worker_engine(spec: dict[str, Any]):
    """(family, config, params, Engine) from a JSON-safe spec dict.

    Every process that must agree on model bytes — router-side reference
    engines, CLI pod workers, serve_bench A/B drivers — builds through
    this one function: `init_params(cfg, key(seed))` is deterministic,
    so identical specs give identical params in different processes."""
    import jax

    from ...engine import Engine

    family_name = spec.get("family", "gpt2")
    if family_name == "llama":
        from ....models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from ....models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    params = family.init_params(cfg, jax.random.key(int(spec.get("seed", 0))))
    engine = Engine(family, cfg, params, engine_config_from_spec(spec))
    engine.close()  # a pod worker exports via heartbeats, not side-cars
    return family, cfg, params, engine


def engine_config_from_spec(spec: dict[str, Any], **overrides):
    """`EngineConfig` from the JSON-safe spec — shared with the router
    CLI, which needs the matching config without paying for an engine."""
    import jax.numpy as jnp

    from ...engine import EngineConfig

    cache_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        spec.get("cache_dtype", "float32")]
    kwargs = dict(
        num_slots=int(spec.get("num_slots", 4)),
        max_len=int(spec.get("max_len", 64)),
        prefill_chunk=int(spec.get("prefill_chunk", 8)),
        max_queue=int(spec.get("max_queue", 64)),
        page_size=int(spec.get("page_size", 8)),
        cache_dtype=cache_dtype,
        kv_dtype=spec.get("kv_dtype"),
        prefix_cache=bool(spec.get("prefix_cache", True)),
        seed=int(spec.get("seed", 0)),
    )
    kwargs.update(overrides)
    return EngineConfig(**kwargs)


@dataclasses.dataclass
class _Job:
    """One flight's worker-side state."""

    flight_id: int
    attempt: int
    mode: str                 # "prefill" | "decode"
    internal: Any
    sent_tokens: int = 0      # decode: tokens already synced at least once
    sent_done: bool = False
    started_at: float = 0.0   # worker clock; bounds this job's spans


class WorkerServer:
    """One engine + one channel to the router. See module docstring."""

    def __init__(self, engine, channel: Channel, worker_id: int,
                 role: str = "decode", heartbeat_interval_s: float = 0.5,
                 clock=time.monotonic, export_spans: bool = True,
                 span_export_limit: int = 256):
        self.engine = engine
        self.channel = channel
        self.worker_id = int(worker_id)
        self.role = role
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._clock = clock
        self.transport = PageTransport(engine)
        self.draining = False
        self.done = False
        self._last_heartbeat = -float("inf")
        self._jobs: dict[int, _Job] = {}
        self._admit_pages: dict[int, list] = {}
        self.stale_messages = 0
        # span export: off for in-process workers (they share the
        # router's flight recorder — exporting would double every span),
        # on for real worker processes
        self.export_spans = bool(export_spans)
        self.span_export_limit = int(span_export_limit)
        self._span_cursor = 0
        # the router's last hb_ack, echoed on the next heartbeat — the
        # two middle timestamps of the NTP exchange the router completes
        self._last_ack: dict | None = None
        self._last_step_s = 0.0
        # the admit hook mirrors PodRouter._record_admit: a short prompt
        # can admit, prefill and retire inside ONE engine.step(), and the
        # alloc dies with the slot — snapshot pages the moment they exist
        engine.on_admit = self._record_admit
        self._send(Message("hello", {
            "worker_id": self.worker_id, "role": self.role,
            "slots": len(engine.scheduler.slots),
            "pages_free": engine.allocator.pages_free,
        }))

    # -- plumbing ------------------------------------------------------------

    def _record_admit(self, slot, req) -> None:
        self._admit_pages[id(req)] = list(slot.alloc.pages)

    def _send(self, msg: Message) -> None:
        try:
            self.channel.send(msg)
        except ConnectionError:
            self.done = True  # router gone: nothing left to serve

    @staticmethod
    def _trace_context(meta: dict) -> tuple[str | None, int, bool]:
        """(trace_id, parent_span_id, sampled) from a job-bearing
        message's optional `traceparent` meta. Malformed or absent ->
        (None, 0, False): tracing can degrade, never break dataflow."""
        parsed = parse_traceparent(meta.get("traceparent"))
        if parsed is None:
            return None, 0, False
        trace_id, parent_hex = parsed
        try:
            parent = int(parent_hex, 16)
        except ValueError:
            parent = 0
        return trace_id, parent, bool(meta.get("sampled", False))

    def _stale(self, meta: dict) -> bool:
        """True when a job-bearing message is from a superseded attempt
        (dup/reorder of a replayed flight) — dropped, counted."""
        job = self._jobs.get(int(meta["flight_id"]))
        if job is not None and int(meta["attempt"]) <= job.attempt \
                and job.mode is not None:
            self.stale_messages += 1
            return True
        return False

    # -- message handlers ----------------------------------------------------

    def _handle(self, msg: Message) -> None:
        meta = msg.meta
        if msg.kind == "submit":
            if self._stale(meta):
                return
            self._evict(int(meta["flight_id"]))
            prompt, key_raw = msg.buffers
            trace_id, parent, sampled = self._trace_context(meta)
            internal = self.engine.submit(
                np.asarray(prompt, np.int32),
                max_new_tokens=int(meta["budget"]),
                temperature=float(meta["temperature"]),
                key=np.asarray(key_raw, np.uint32),
                trace_id=trace_id, trace_parent=parent,
                trace_sampled=sampled)
            self._jobs[int(meta["flight_id"])] = _Job(
                flight_id=int(meta["flight_id"]),
                attempt=int(meta["attempt"]), mode="prefill",
                internal=internal, started_at=self._clock())
        elif msg.kind == "shipment":
            if self._stale(meta):
                return
            self._evict(int(meta["flight_id"]))
            shipment = shipment_from_message(msg)
            t0 = self._clock()
            placed = place_shipment(self.engine, self.transport, shipment,
                                    t0)
            if placed is None:
                # no slot/pages here right now — the router re-routes or
                # replays; refusing is cheaper than deadlocking a slot
                self._send(Message("install_failed", {
                    "flight_id": int(meta["flight_id"]),
                    "attempt": int(meta["attempt"]),
                    "worker_id": self.worker_id}))
                return
            internal, _slot, _alloc = placed
            # join the router's trace AFTER placement: the internal is
            # built by place_shipment, not engine.submit
            trace_id, parent, sampled = self._trace_context(meta)
            if trace_id is not None:
                from ...engine import prepare_request_tracing

                prepare_request_tracing(internal, trace_id, parent, sampled)
                if internal.trace_sampled:
                    # decode start on THIS worker: pages landed, slot
                    # adopted — the third leg of the cross-process
                    # timeline (prefill -> page_transfer -> install)
                    record_span(
                        "serving.pod.install", t0, self._clock(),
                        trace=internal.trace_id, parent=parent,
                        worker=self.worker_id,
                        flight_id=int(meta["flight_id"]),
                        attempt=int(meta["attempt"]),
                        pages=shipment.n_prompt_pages)
            self._jobs[int(meta["flight_id"])] = _Job(
                flight_id=int(meta["flight_id"]),
                attempt=int(meta["attempt"]), mode="decode",
                internal=internal, sent_tokens=1, started_at=t0)
        elif msg.kind == "hb_ack":
            # router's receipt stamp for one of our heartbeats; echo it
            # (plus OUR receipt time of this ack) on the next heartbeat —
            # the router then holds all four NTP timestamps
            self._last_ack = {
                "router_t": float(meta.get("router_t", 0.0)),
                "worker_recv_t": self._clock(),
            }
        elif msg.kind == "incident_request":
            self._send(Message("incident_dumps", {
                "req_id": meta.get("req_id"),
                "worker_id": self.worker_id,
                "dumps": self.incident_dumps(),
            }))
        elif msg.kind == "cancel":
            job = self._jobs.pop(int(meta["flight_id"]), None)
            if job is not None:
                self._admit_pages.pop(id(job.internal), None)
                self.engine.cancel(job.internal)
        elif msg.kind == "finish":
            job = self._jobs.pop(int(meta["flight_id"]), None)
            if job is not None:
                self._admit_pages.pop(id(job.internal), None)
                self.engine.finish(job.internal)
        elif msg.kind == "set_role":
            self.role = str(meta["role"])
        elif msg.kind == "reset":
            # rejoin after a partition the router already recovered from:
            # every local flight was replayed elsewhere — drop them all
            for job in list(self._jobs.values()):
                self._admit_pages.pop(id(job.internal), None)
                if not job.internal.done:
                    self.engine.cancel(job.internal)
            self._jobs.clear()
        elif msg.kind == "drain":
            self.draining = True

    def _evict(self, flight_id: int) -> None:
        """A NEWER attempt for a flight we already hold: the old
        internal is dead weight — cancel it before starting over."""
        job = self._jobs.pop(flight_id, None)
        if job is not None:
            self._admit_pages.pop(id(job.internal), None)
            if not job.internal.done:
                self.engine.cancel(job.internal)

    # -- outbound ------------------------------------------------------------

    def _harvest_prefill(self) -> None:
        """Ship every prefill job whose first token exists (mirror of
        PodRouter._harvest, result crossing the channel instead of a
        deque). Extraction happens HERE, before the engine steps again —
        a retired slot's pages are only reallocatable at the next
        admission, which cannot happen before the next step."""
        now = self._clock()
        for job in list(self._jobs.values()):
            if job.mode != "prefill":
                continue
            internal = job.internal
            if not internal.tokens and not internal.done:
                continue
            del self._jobs[job.flight_id]
            if internal.done and internal.status is not RequestStatus.FINISHED:
                self._admit_pages.pop(id(internal), None)
                self._send(Message("prefill_failed", {
                    "flight_id": job.flight_id, "attempt": job.attempt,
                    "worker_id": self.worker_id,
                    "status": internal.status.value}))
                continue
            pages = self._admit_pages.pop(id(internal), None)
            shipment = self.transport.extract_shipment(
                pages, internal, src_worker=self.worker_id, extracted_at=now)
            if internal.trace_sampled:
                # prefill on THIS worker, submit->extract: the first leg
                # of the cross-process timeline (ends where the router's
                # page_transfer span begins)
                record_span(
                    "serving.pod.prefill", job.started_at, now,
                    trace=internal.trace_id, parent=internal.trace_parent,
                    worker=self.worker_id, flight_id=job.flight_id,
                    attempt=job.attempt)
            if not internal.done:
                # retire as FINISHED so the prompt enters this worker's
                # prefix tree: shared prefixes prefill once per worker
                self.engine.finish(internal)
            self._send(shipment_to_message(
                shipment, flight_id=job.flight_id, attempt=job.attempt,
                worker_id=self.worker_id))

    def _sync_decode(self) -> None:
        """Full-state token sync for every decode job with news."""
        for job in list(self._jobs.values()):
            if job.mode != "decode":
                continue
            internal = job.internal
            if len(internal.tokens) == job.sent_tokens and not internal.done:
                continue
            self._send(Message("tokens", {
                "flight_id": job.flight_id, "attempt": job.attempt,
                "worker_id": self.worker_id,
                "tokens": [int(t) for t in internal.tokens],
                "logprobs": [float(lp) for lp in internal.logprobs],
                "done": bool(internal.done),
                "status": internal.status.value,
            }))
            job.sent_tokens = len(internal.tokens)
            if internal.done:
                job.sent_done = True
                del self._jobs[job.flight_id]

    def _busy_hint(self) -> bool:
        """True when the NEXT engine.step() may outlast the heartbeat
        interval: a program this worker's pending work needs has never
        compiled (first-compile is the documented phantom-loss hazard),
        or the previous step already ran long. Announced BEFORE stepping
        so the router defers its `heartbeat_timeout` verdict while this
        worker is provably busy-not-dead."""
        if not self.engine.scheduler.has_work():
            return False
        if self._last_step_s > max(self.heartbeat_interval_s, 0.05):
            return True
        compiles = self.engine.compile_stats()
        modes = {j.mode for j in self._jobs.values()}
        if "decode" not in modes or not modes:
            # queued/prefill work ahead: needs admit + prefill programs
            if not compiles.get("admit") or not compiles.get("prefill"):
                return True
        if "decode" in modes and not compiles.get("decode"):
            return True
        return False

    def incident_dumps(self) -> dict:
        """This worker's contribution to a fleet incident bundle: its
        channel-facing job table plus the engine's own dumps, forced
        JSON-safe (the reply crosses the wire codec — one unserializable
        value must not cost the router the whole stanza)."""
        out: dict[str, Any] = {
            "worker_id": self.worker_id,
            "role": self.role,
            "pid": os.getpid(),
            "draining": self.draining,
            "stale_messages": self.stale_messages,
            "jobs": [{
                "flight_id": j.flight_id, "attempt": j.attempt,
                "mode": j.mode, "tokens": len(j.internal.tokens),
                "done": bool(j.internal.done),
            } for j in self._jobs.values()],
        }
        try:
            out["engine"] = self.engine.incident_dumps()
        except Exception as e:
            out["engine"] = {"error": f"{type(e).__name__}: {e}"}
        return json.loads(json.dumps(out, default=str))

    def _maybe_heartbeat(self, force: bool = False,
                         busy: bool = False, lean: bool = False) -> None:
        now = self._clock()
        if not force and now - self._last_heartbeat < self.heartbeat_interval_s:
            return
        self._last_heartbeat = now
        if lean:
            # the busy pre-announce is latency-critical (it must be in
            # flight before the device block) — ship only liveness + the
            # NTP stamps, not the registry snapshot
            meta = {"worker_id": self.worker_id, "role": self.role,
                    "t": now, "pid": os.getpid(),
                    "draining": self.draining, "busy": bool(busy)}
            if self._last_ack is not None:
                meta["ack"] = self._last_ack
            self._send(Message("heartbeat", meta))
            return
        eng = self.engine
        meta = {
            "worker_id": self.worker_id, "role": self.role, "t": now,
            "pid": os.getpid(),
            "draining": self.draining,
            "busy": bool(busy or self._busy_hint()),
            "stats": {
                "slots": len(eng.scheduler.slots),
                "live_slots": eng.scheduler.live_slots,
                "queue_depth": eng.scheduler.queue_depth,
                "pages_free": eng.allocator.pages_free,
                "pages_in_use": eng.allocator.pages_in_use,
            },
            "compiles": {**eng.compile_stats(),
                         **self.transport.compile_stats()},
            # the registry snapshot IS the telemetry merge payload:
            # counters/gauges/sketches aggregate router-side without a
            # jax process group (telemetry/aggregate.py)
            "snapshot": eng.registry.snapshot(include_sketch=True),
        }
        if self._last_ack is not None:
            # the NTP echo: (router send, our receipt) of the last ack;
            # together with this heartbeat's ("t", router receipt) the
            # router holds all four timestamps of one round trip
            meta["ack"] = self._last_ack
        if self.export_spans and tracing_enabled():
            spans, cursor = drain_spans(self._span_cursor,
                                        limit=self.span_export_limit)
            if cursor != self._span_cursor:
                self._span_cursor = cursor
                if spans:
                    meta["spans"] = spans
                # the high-water mark dedups ingestion under heartbeat
                # dup/reorder (FlakyTransport can deliver one twice)
                meta["span_seq"] = cursor
        self._send(Message("heartbeat", meta))

    # -- drive ---------------------------------------------------------------

    def run_once(self) -> bool:
        """One deterministic pump. Returns True when anything moved."""
        if self.done:
            return False
        if self.channel.closed:
            self.done = True
            return False
        msgs = self.channel.poll()
        for msg in msgs:
            self._handle(msg)
        # hb_acks are clock-sync plumbing, not progress: counting them
        # would ping-pong with our own heartbeats and keep an idle pod's
        # step() returning True forever
        worked = any(m.kind != "hb_ack" for m in msgs)
        if self.engine.scheduler.has_work():
            if self._busy_hint():
                # announce the device block BEFORE entering it: the
                # heartbeat must be in flight while we cannot send
                self._maybe_heartbeat(force=True, busy=True, lean=True)
            t0 = self._clock()
            self.engine.step()
            self._last_step_s = self._clock() - t0
            worked = True
        self._harvest_prefill()
        self._sync_decode()
        self._maybe_heartbeat()
        if self.draining and not self._jobs \
                and not self.engine.scheduler.has_work():
            self._send(Message("bye", {"worker_id": self.worker_id}))
            self.done = True
        return worked

    def run(self, poll_interval_s: float = 0.002) -> None:
        """Blocking loop for real worker processes; returns when drained
        or the router goes away. SIGTERM -> drain is wired by the CLI."""
        while not self.done:
            if not self.run_once() and not self.done:
                time.sleep(poll_interval_s)
