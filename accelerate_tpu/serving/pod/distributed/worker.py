"""Pod worker: one Engine behind a channel, role assigned by the router.

A worker is deliberately role-AGNOSTIC: it owns a single `Engine` plus
its `PageTransport` and executes whatever the router sends — `submit`
messages (prefill a prompt, ship its pages back) or `shipment` messages
(land the pages, decode to completion, stream token state back). "Role"
is a *label* the router uses for placement preference and elastic
rebalancing; converting a worker between prefill and decode is a
router-side bookkeeping flip plus a `set_role` notice, never a process
restart. That is also what makes single-survivor recovery possible: if
every decode worker dies, the remaining prefill worker simply starts
receiving shipments.

Token delivery is FULL-STATE sync, not deltas: every `tokens` message
carries the internal request's complete token/logprob lists. Resending
the whole (small — bounded by max_new_tokens) list makes delivery
idempotent and monotone, so dropped, duplicated, or reordered messages
need no acks and no sequence recovery — the router just keeps the
longest prefix it has seen for the flight's current attempt. A
production transport would delta-encode with acks; the exactness and
recovery semantics are identical.

Every job-bearing message carries ``(flight_id, attempt)`` and every
reply echoes it. The router bumps `attempt` on each replay, so a
duplicate or late message from an earlier attempt is recognizably stale
and dropped on both sides — this is what makes at-least-once delivery
safe under re-prefill recovery (no token delivered twice).

`run_once()` is one deterministic pump (poll, dispatch, step, harvest,
sync, heartbeat) — the in-process tests drive it directly under a fake
clock. `run()` wraps it in the real loop with SIGTERM drain mirroring
`serve`: finish in-flight work, say `bye`, exit.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from ...scheduler import RequestStatus
from ..transfer import PageTransport, place_shipment
from .transport import Channel
from .wire import Message, shipment_from_message, shipment_to_message

__all__ = ["WorkerServer", "build_worker_engine", "engine_config_from_spec",
           "ENGINE_SPEC_KEYS"]

# the engine-spec dict shared by CLI workers / tests / serve_bench so
# separate processes build byte-identical engines (family + seed pin
# the params; the rest pins the compiled-shape envelope)
ENGINE_SPEC_KEYS = ("family", "seed", "num_slots", "max_len",
                    "prefill_chunk", "page_size", "max_queue",
                    "cache_dtype", "kv_dtype", "prefix_cache")


def build_worker_engine(spec: dict[str, Any]):
    """(family, config, params, Engine) from a JSON-safe spec dict.

    Every process that must agree on model bytes — router-side reference
    engines, CLI pod workers, serve_bench A/B drivers — builds through
    this one function: `init_params(cfg, key(seed))` is deterministic,
    so identical specs give identical params in different processes."""
    import jax

    from ...engine import Engine

    family_name = spec.get("family", "gpt2")
    if family_name == "llama":
        from ....models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from ....models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    params = family.init_params(cfg, jax.random.key(int(spec.get("seed", 0))))
    engine = Engine(family, cfg, params, engine_config_from_spec(spec))
    engine.close()  # a pod worker exports via heartbeats, not side-cars
    return family, cfg, params, engine


def engine_config_from_spec(spec: dict[str, Any], **overrides):
    """`EngineConfig` from the JSON-safe spec — shared with the router
    CLI, which needs the matching config without paying for an engine."""
    import jax.numpy as jnp

    from ...engine import EngineConfig

    cache_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        spec.get("cache_dtype", "float32")]
    kwargs = dict(
        num_slots=int(spec.get("num_slots", 4)),
        max_len=int(spec.get("max_len", 64)),
        prefill_chunk=int(spec.get("prefill_chunk", 8)),
        max_queue=int(spec.get("max_queue", 64)),
        page_size=int(spec.get("page_size", 8)),
        cache_dtype=cache_dtype,
        kv_dtype=spec.get("kv_dtype"),
        prefix_cache=bool(spec.get("prefix_cache", True)),
        seed=int(spec.get("seed", 0)),
    )
    kwargs.update(overrides)
    return EngineConfig(**kwargs)


@dataclasses.dataclass
class _Job:
    """One flight's worker-side state."""

    flight_id: int
    attempt: int
    mode: str                 # "prefill" | "decode"
    internal: Any
    sent_tokens: int = 0      # decode: tokens already synced at least once
    sent_done: bool = False


class WorkerServer:
    """One engine + one channel to the router. See module docstring."""

    def __init__(self, engine, channel: Channel, worker_id: int,
                 role: str = "decode", heartbeat_interval_s: float = 0.5,
                 clock=time.monotonic):
        self.engine = engine
        self.channel = channel
        self.worker_id = int(worker_id)
        self.role = role
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._clock = clock
        self.transport = PageTransport(engine)
        self.draining = False
        self.done = False
        self._last_heartbeat = -float("inf")
        self._jobs: dict[int, _Job] = {}
        self._admit_pages: dict[int, list] = {}
        self.stale_messages = 0
        # the admit hook mirrors PodRouter._record_admit: a short prompt
        # can admit, prefill and retire inside ONE engine.step(), and the
        # alloc dies with the slot — snapshot pages the moment they exist
        engine.on_admit = self._record_admit
        self._send(Message("hello", {
            "worker_id": self.worker_id, "role": self.role,
            "slots": len(engine.scheduler.slots),
            "pages_free": engine.allocator.pages_free,
        }))

    # -- plumbing ------------------------------------------------------------

    def _record_admit(self, slot, req) -> None:
        self._admit_pages[id(req)] = list(slot.alloc.pages)

    def _send(self, msg: Message) -> None:
        try:
            self.channel.send(msg)
        except ConnectionError:
            self.done = True  # router gone: nothing left to serve

    def _stale(self, meta: dict) -> bool:
        """True when a job-bearing message is from a superseded attempt
        (dup/reorder of a replayed flight) — dropped, counted."""
        job = self._jobs.get(int(meta["flight_id"]))
        if job is not None and int(meta["attempt"]) <= job.attempt \
                and job.mode is not None:
            self.stale_messages += 1
            return True
        return False

    # -- message handlers ----------------------------------------------------

    def _handle(self, msg: Message) -> None:
        meta = msg.meta
        if msg.kind == "submit":
            if self._stale(meta):
                return
            self._evict(int(meta["flight_id"]))
            prompt, key_raw = msg.buffers
            internal = self.engine.submit(
                np.asarray(prompt, np.int32),
                max_new_tokens=int(meta["budget"]),
                temperature=float(meta["temperature"]),
                key=np.asarray(key_raw, np.uint32),
                trace_sampled=False)
            self._jobs[int(meta["flight_id"])] = _Job(
                flight_id=int(meta["flight_id"]),
                attempt=int(meta["attempt"]), mode="prefill",
                internal=internal)
        elif msg.kind == "shipment":
            if self._stale(meta):
                return
            self._evict(int(meta["flight_id"]))
            shipment = shipment_from_message(msg)
            placed = place_shipment(self.engine, self.transport, shipment,
                                    self._clock())
            if placed is None:
                # no slot/pages here right now — the router re-routes or
                # replays; refusing is cheaper than deadlocking a slot
                self._send(Message("install_failed", {
                    "flight_id": int(meta["flight_id"]),
                    "attempt": int(meta["attempt"]),
                    "worker_id": self.worker_id}))
                return
            internal, _slot, _alloc = placed
            self._jobs[int(meta["flight_id"])] = _Job(
                flight_id=int(meta["flight_id"]),
                attempt=int(meta["attempt"]), mode="decode",
                internal=internal, sent_tokens=1)
        elif msg.kind == "cancel":
            job = self._jobs.pop(int(meta["flight_id"]), None)
            if job is not None:
                self._admit_pages.pop(id(job.internal), None)
                self.engine.cancel(job.internal)
        elif msg.kind == "finish":
            job = self._jobs.pop(int(meta["flight_id"]), None)
            if job is not None:
                self._admit_pages.pop(id(job.internal), None)
                self.engine.finish(job.internal)
        elif msg.kind == "set_role":
            self.role = str(meta["role"])
        elif msg.kind == "reset":
            # rejoin after a partition the router already recovered from:
            # every local flight was replayed elsewhere — drop them all
            for job in list(self._jobs.values()):
                self._admit_pages.pop(id(job.internal), None)
                if not job.internal.done:
                    self.engine.cancel(job.internal)
            self._jobs.clear()
        elif msg.kind == "drain":
            self.draining = True

    def _evict(self, flight_id: int) -> None:
        """A NEWER attempt for a flight we already hold: the old
        internal is dead weight — cancel it before starting over."""
        job = self._jobs.pop(flight_id, None)
        if job is not None:
            self._admit_pages.pop(id(job.internal), None)
            if not job.internal.done:
                self.engine.cancel(job.internal)

    # -- outbound ------------------------------------------------------------

    def _harvest_prefill(self) -> None:
        """Ship every prefill job whose first token exists (mirror of
        PodRouter._harvest, result crossing the channel instead of a
        deque). Extraction happens HERE, before the engine steps again —
        a retired slot's pages are only reallocatable at the next
        admission, which cannot happen before the next step."""
        now = self._clock()
        for job in list(self._jobs.values()):
            if job.mode != "prefill":
                continue
            internal = job.internal
            if not internal.tokens and not internal.done:
                continue
            del self._jobs[job.flight_id]
            if internal.done and internal.status is not RequestStatus.FINISHED:
                self._admit_pages.pop(id(internal), None)
                self._send(Message("prefill_failed", {
                    "flight_id": job.flight_id, "attempt": job.attempt,
                    "worker_id": self.worker_id,
                    "status": internal.status.value}))
                continue
            pages = self._admit_pages.pop(id(internal), None)
            shipment = self.transport.extract_shipment(
                pages, internal, src_worker=self.worker_id, extracted_at=now)
            if not internal.done:
                # retire as FINISHED so the prompt enters this worker's
                # prefix tree: shared prefixes prefill once per worker
                self.engine.finish(internal)
            self._send(shipment_to_message(
                shipment, flight_id=job.flight_id, attempt=job.attempt,
                worker_id=self.worker_id))

    def _sync_decode(self) -> None:
        """Full-state token sync for every decode job with news."""
        for job in list(self._jobs.values()):
            if job.mode != "decode":
                continue
            internal = job.internal
            if len(internal.tokens) == job.sent_tokens and not internal.done:
                continue
            self._send(Message("tokens", {
                "flight_id": job.flight_id, "attempt": job.attempt,
                "worker_id": self.worker_id,
                "tokens": [int(t) for t in internal.tokens],
                "logprobs": [float(lp) for lp in internal.logprobs],
                "done": bool(internal.done),
                "status": internal.status.value,
            }))
            job.sent_tokens = len(internal.tokens)
            if internal.done:
                job.sent_done = True
                del self._jobs[job.flight_id]

    def _maybe_heartbeat(self) -> None:
        now = self._clock()
        if now - self._last_heartbeat < self.heartbeat_interval_s:
            return
        self._last_heartbeat = now
        eng = self.engine
        self._send(Message("heartbeat", {
            "worker_id": self.worker_id, "role": self.role, "t": now,
            "draining": self.draining,
            "stats": {
                "slots": len(eng.scheduler.slots),
                "live_slots": eng.scheduler.live_slots,
                "queue_depth": eng.scheduler.queue_depth,
                "pages_free": eng.allocator.pages_free,
                "pages_in_use": eng.allocator.pages_in_use,
            },
            "compiles": {**eng.compile_stats(),
                         **self.transport.compile_stats()},
            # the registry snapshot IS the telemetry merge payload:
            # counters/gauges/sketches aggregate router-side without a
            # jax process group (telemetry/aggregate.py)
            "snapshot": eng.registry.snapshot(include_sketch=True),
        }))

    # -- drive ---------------------------------------------------------------

    def run_once(self) -> bool:
        """One deterministic pump. Returns True when anything moved."""
        if self.done:
            return False
        if self.channel.closed:
            self.done = True
            return False
        msgs = self.channel.poll()
        for msg in msgs:
            self._handle(msg)
        worked = bool(msgs)
        if self.engine.scheduler.has_work():
            self.engine.step()
            worked = True
        self._harvest_prefill()
        self._sync_decode()
        self._maybe_heartbeat()
        if self.draining and not self._jobs \
                and not self.engine.scheduler.has_work():
            self._send(Message("bye", {"worker_id": self.worker_id}))
            self.done = True
        return worked

    def run(self, poll_interval_s: float = 0.002) -> None:
        """Blocking loop for real worker processes; returns when drained
        or the router goes away. SIGTERM -> drain is wired by the CLI."""
        while not self.done:
            if not self.run_once() and not self.done:
                time.sleep(poll_interval_s)
