"""Pod layer 2 plumbing: KV pages as the unit of prefill->decode transfer.

The paged cache (serving/cache.py) made a request's KV state a list of
fixed-size, location-free pages — which is exactly what makes
disaggregation possible: a prefill worker computes a prompt's KV into its
own pool, and the pages (pool rows + the page-table fragment naming them)
ship to a decode worker that owns the slot for the request's decode
lifetime. This module is the device-side half of that hand-off:

- `extract`: gather one slot's table row out of the pool into a dense
  [L, pages_per_slot, page_size, H, D] block. FIXED shape — the block
  always spans the full table row (trash-padded rows gather the trash
  page) so every extraction hits the same compiled program. The host then
  keeps only the `n_prompt_pages` that carry real prompt KV; on a real
  pod this block is what crosses DCN/ICI (a production transport would
  ship the prompt pages only — the fixed-shape block is the
  compile-count-flat testing/CPU form of the same hand-off).

- `install`: scatter a shipped block into the decode worker's pool at
  its freshly allocated page indices (row padded with the trash page
  beyond the prompt pages, so the dead lanes write nowhere), and seed
  the slot's last-token register with the first generated token the
  prefill worker sampled. Also fixed-shape, also one compile.

Correctness under sharing: the decode worker's allocator may have
matched a prefix of the shipped prompt in its OWN radix tree, in which
case the leading allocated pages are mapped copy-on-write. Installing
over them is safe for the same reason prefill's window scatter is: both
workers run identical programs over identical params, so a shared prompt
page's shipped bytes ARE the cached page's bytes — a value-identical
rewrite, however many sharers race. Rows past `prompt_len` in the last
shipped page (chunk padding, or a decode step the prefill worker ran
before the router reclaimed the slot) are masked by the position
invariant and overwritten by the decode worker's own appends.

`KVPageShipment` is deliberately plain host data (numpy + ints): it IS
the wire format. In-process pods hand the arrays over directly; a
multi-host pod serializes exactly these fields.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVPageShipment", "PageTransport", "place_shipment"]


@dataclasses.dataclass
class KVPageShipment:
    """One prompt's prefilled KV state, in transit prefill -> decode.

    `k_pages`/`v_pages` are the fixed-shape extracted block
    ([L, pages_per_slot, page_size, H, D] host numpy); only the first
    `n_prompt_pages` carry prompt KV (the rest rode along for shape
    stability and are dropped at install). `first_token` is the first
    generated token — sampled on the prefill worker from the final
    prompt logits, so the decode worker starts from exactly the state a
    single-engine prefill would have left."""

    prompt: np.ndarray
    first_token: int
    n_prompt_pages: int
    k_pages: np.ndarray
    v_pages: np.ndarray
    key_raw: np.ndarray          # uint32[2] — the request's sampling key
    temperature: float
    max_new_tokens: int
    eos_token_id: int | None
    src_worker: int = -1
    extracted_at: float = 0.0    # router clock; the page_transfer span start
    # the first token's model logprob (models emit per-token logprobs —
    # ISSUE 12), so the decode-side internal's logprob list stays aligned
    # with its tokens; None only for shipments from pre-logprob senders
    first_logprob: float | None = None
    # int8 pools ship their codes as-is plus the per-row-per-head scale
    # blocks ([L, pages_per_slot, page_size, H]) — the wire carries half
    # the bytes of a bf16 shipment; None on bf16 pools
    k_scales: np.ndarray | None = None
    v_scales: np.ndarray | None = None

    @property
    def page_bytes(self) -> int:
        """Real payload bytes (prompt pages only), the number a transport
        would put on the wire."""
        per_page = self.k_pages[:, 0].nbytes + self.v_pages[:, 0].nbytes
        if self.k_scales is not None:
            per_page += self.k_scales[:, 0].nbytes + self.v_scales[:, 0].nbytes
        return self.n_prompt_pages * per_page


class PageTransport:
    """Per-worker jitted extract/install pair.

    Shapes are fixed by the worker's pool, so each side compiles exactly
    once per engine lifetime — the pod's compile count stays flat per
    role however the request mix, prompt lengths, or hit/miss pattern
    change. Meshed workers pin `install`'s out_shardings to the engine's
    pool layout for the same fixed-point reason the engine pins its own
    programs (serving/pod/mesh.py)."""

    def __init__(self, engine):
        self._engine = engine
        self._quantized = engine.cache.quantized
        install_out = None
        if engine._mesh_shardings is not None:
            cache_sh, rep = engine._mesh_shardings
            install_out = (cache_sh, rep)

        if self._quantized:
            # int8 pool: codes ship verbatim with their scale blocks —
            # no dequant/requant round-trip (which would drift the codes;
            # shipped pages must stay byte-identical to the prefill
            # worker's, the same invariant COW sharing relies on)
            @jax.jit
            def extract(cache, rows):
                return (cache.k[:, rows], cache.v[:, rows],
                        cache.k_scale[:, rows], cache.v_scale[:, rows])

            @partial(jax.jit, donate_argnums=(0, 1),
                     out_shardings=install_out)
            def install(cache, tokens, slot, rows, k_pages, v_pages,
                        first_tok, k_scales, v_scales):
                return (
                    dataclasses.replace(
                        cache,
                        k=cache.k.at[:, rows].set(k_pages),
                        v=cache.v.at[:, rows].set(v_pages),
                        k_scale=cache.k_scale.at[:, rows].set(k_scales),
                        v_scale=cache.v_scale.at[:, rows].set(v_scales),
                    ),
                    tokens.at[slot].set(first_tok),
                )
        else:
            @jax.jit
            def extract(cache, rows):
                # rows: [pages_per_slot] int32 (traced data — any mapping,
                # one program); gathers [L, P, ps, H, D] per buffer
                return cache.k[:, rows], cache.v[:, rows]

            @partial(jax.jit, donate_argnums=(0, 1),
                     out_shardings=install_out)
            def install(cache, tokens, slot, rows, k_pages, v_pages,
                        first_tok):
                # trash-padded `rows` entries scatter their pages into the
                # reserved trash page — dead writes, never a live page
                return (
                    dataclasses.replace(
                        cache,
                        k=cache.k.at[:, rows].set(
                            k_pages.astype(cache.k.dtype)),
                        v=cache.v.at[:, rows].set(
                            v_pages.astype(cache.v.dtype)),
                    ),
                    tokens.at[slot].set(first_tok),
                )

        self._extract_p = extract
        self._install_p = install

    def compile_stats(self) -> dict[str, int]:
        return {
            "extract": self._extract_p._cache_size(),
            "install": self._install_p._cache_size(),
        }

    # -- prefill side --------------------------------------------------------

    def extract_shipment(self, pages: list[int], request,
                         src_worker: int = -1,
                         extracted_at: float = 0.0) -> KVPageShipment:
        """Pull a prefilled slot's pages off the prefill worker into a
        shipment. `pages` is the slot's allocation (recorded at
        admission); the request must still hold them — extract BEFORE the
        slot retires or the pool may reallocate the partial last page."""
        eng = self._engine
        row = np.full((eng.cache.pages_per_slot,), eng.cache.trash_page,
                      np.int32)
        row[:len(pages)] = pages
        eng._strict_audit("extract", self._extract_p, (eng.cache, row))
        out = self._extract_p(eng.cache, row)
        k_scales = v_scales = None
        if self._quantized:
            k_pages, v_pages, k_scales, v_scales = out
            k_scales, v_scales = np.asarray(k_scales), np.asarray(v_scales)
        else:
            k_pages, v_pages = out
        n_prompt = -(-request.prompt_len // eng.cache.page_size)
        return KVPageShipment(
            prompt=request.prompt,
            first_token=int(request.tokens[0]),
            first_logprob=(float(request.logprobs[0])
                           if request.logprobs else None),
            n_prompt_pages=n_prompt,
            k_pages=np.asarray(k_pages),
            v_pages=np.asarray(v_pages),
            key_raw=np.asarray(jax.device_get(request.key), np.uint32),
            temperature=request.temperature,
            max_new_tokens=request.max_new_tokens,
            eos_token_id=request.eos_token_id,
            src_worker=src_worker,
            extracted_at=extracted_at,
            k_scales=k_scales,
            v_scales=v_scales,
        )

    # -- decode side ---------------------------------------------------------

    def install_shipment(self, shipment: KVPageShipment, slot_index: int,
                         alloc) -> None:
        """Land a shipment in this decode worker: pages scattered into
        the allocation's indices, the slot's length set to the full
        prompt (`reused_len=prompt_len` through the ordinary admit
        program — to the pool a shipped prompt IS a fully reused
        prefix), key/temperature installed, last-token register seeded.
        After this the slot decodes exactly as if the worker had
        prefilled the prompt itself."""
        eng = self._engine
        row = np.full((eng.cache.pages_per_slot,), eng.cache.trash_page,
                      np.int32)
        row[:shipment.n_prompt_pages] = alloc.pages[:shipment.n_prompt_pages]
        args = (eng.cache, eng._tokens, jnp.int32(slot_index), row,
                shipment.k_pages, shipment.v_pages,
                jnp.int32(shipment.first_token))
        if self._quantized:
            args += (shipment.k_scales, shipment.v_scales)
        eng._strict_audit("install", self._install_p, args)
        eng.cache, eng._tokens = self._install_p(*args)
        admit_args = (eng.cache, eng._slot_keys, eng._temps,
                      jnp.int32(slot_index),
                      jnp.asarray(shipment.key_raw, jnp.uint32),
                      jnp.float32(shipment.temperature),
                      jnp.int32(int(shipment.prompt.shape[0])))
        # a pure decode worker first meets the admit program HERE — the
        # strict audit must still cover it once
        eng._strict_audit("admit", eng._admit_p, admit_args)
        eng.cache, eng._slot_keys, eng._temps = eng._admit_p(*admit_args)


def place_shipment(engine, transport: PageTransport, shipment: KVPageShipment,
                   now: float):
    """Land one shipment on `engine` end-to-end: internal Request built
    from the shipment, pages allocated (prefix-reuse aware), slot adopted
    RUNNING, table row written, pages installed, stale host mirrors
    dropped, first token + admission booked. Returns
    ``(internal, slot, alloc)`` or ``None`` when the engine has no free
    slot or pages right now (nothing mutated on None).

    This is the single placement path shared by the in-process
    `PodRouter._try_install` and the multi-host worker's `install`
    handler — the process boundary must not fork the landing semantics.
    """
    from ..scheduler import Request

    if engine.scheduler.live_slots >= len(engine.scheduler.slots):
        return None
    internal = Request(
        prompt=shipment.prompt,
        max_new_tokens=shipment.max_new_tokens,
        temperature=shipment.temperature,
        key=shipment.key_raw,
        eos_token_id=shipment.eos_token_id,
    )
    # nothing that can raise may sit between allocate and the
    # adopt/rollback pair that owns its outcome (ATP201 exception window)
    alloc = engine.allocator.allocate(internal)
    if alloc is None:
        return None
    internal.submitted_at = now
    slot = engine.scheduler.adopt_running(internal, alloc, now=now)
    if slot is None:               # raced: give the pages back
        engine.allocator.rollback(alloc)
        return None
    engine._table[slot.index, :] = engine.cache.trash_page
    engine._table[slot.index, :len(alloc.pages)] = alloc.pages
    transport.install_shipment(shipment, slot.index, alloc)
    # host-resident prefix chunks were re-homed to fresh pages by
    # allocate(); the shipment just wrote those pages with the exact
    # bytes the mirror holds, so the mirror is dead — drop it instead of
    # fetching (skips a host->device copy). After install on purpose:
    # the slot claim must complete before any non-essential bookkeeping
    # call could raise (ATP201 discipline).
    if alloc.swap_ins:
        for node, _page in alloc.swap_ins:
            engine._host_tier.discard(node)
    # seed the first token so EOS/budget accounting continues exactly
    # where the prefill worker left off; its logprob rides the shipment
    # so the internal's logprob list stays index-aligned
    engine.scheduler.note_token(slot, shipment.first_token, now=now,
                                logprob=shipment.first_logprob)
    engine.metrics.note_admission(
        internal.prompt_len, alloc.reused_len,
        host_pages=len(alloc.swap_ins or ()))
    return internal, slot, alloc
