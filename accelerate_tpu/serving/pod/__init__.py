"""Pod-scale serving: mesh-sharded engines + disaggregated workers.

Two composable layers over the continuous-batching engine:

- **Layer 1 (SPMD, `pod.mesh`)** — one engine tensor-parallel over a
  device mesh: params sharded by the repo's path-pattern rules, the
  paged KV pool sharded over heads, program out_shardings pinned so the
  compile count stays flat. `sharded_engine(...)` is the factory;
  `EngineConfig(mesh=...)` is the knob it turns.

- **Layer 2 (MPMD, `pod.router` / `pod.transfer`)** — prefill and
  decode split into dedicated worker groups shipping KV pages:
  `PodRouter` (alias `PodEngine`) exposes the ordinary `ServingEngine`
  API over the fleet, with role assignment, page-transfer bookkeeping,
  and decode-side backpressure handled host-side.

Both layers are proven token-exact against the single-device engine on
seeded traces (tier-1, forced-host-device CPU meshes). See
docs/serving.md "Pod-scale serving".
"""

from .mesh import (
    cache_state_shardings,
    shard_params,
    sharded_engine,
    tensor_mesh,
)
from .router import PodConfig, PodEngine, PodRouter
from .transfer import KVPageShipment, PageTransport

__all__ = [
    "tensor_mesh",
    "shard_params",
    "cache_state_shardings",
    "sharded_engine",
    "PodConfig",
    "PodRouter",
    "PodEngine",
    "KVPageShipment",
    "PageTransport",
]
