"""Pod-scale serving: mesh-sharded engines + disaggregated workers.

Two composable layers over the continuous-batching engine:

- **Layer 1 (SPMD, `pod.mesh`)** — one engine tensor-parallel over a
  device mesh: params sharded by the repo's path-pattern rules, the
  paged KV pool sharded over heads, program out_shardings pinned so the
  compile count stays flat. `sharded_engine(...)` is the factory;
  `EngineConfig(mesh=...)` is the knob it turns.

- **Layer 2 (MPMD, `pod.router` / `pod.transfer`)** — prefill and
  decode split into dedicated worker groups shipping KV pages:
  `PodRouter` (alias `PodEngine`) exposes the ordinary `ServingEngine`
  API over the fleet, with role assignment, page-transfer bookkeeping,
  and decode-side backpressure handled host-side.

- **Layer 3 (multi-host, `pod.distributed`)** — the same dataflow over
  OS processes: a socket wire format for shipments, worker heartbeats,
  re-prefill-from-prompt failure recovery, and elastic role
  rebalancing. `DistributedPodRouter` is the front; `PodRouter` stays
  the in-process `local` transport.

All layers are proven token-exact against the single-device engine on
seeded traces (tier-1, forced-host-device CPU meshes). See
docs/serving.md "Pod-scale serving" and "True multi-host pod".
"""

from .mesh import (
    cache_state_shardings,
    shard_params,
    sharded_engine,
    tensor_mesh,
)
from .router import PodConfig, PodEngine, PodRouter
from .transfer import KVPageShipment, PageTransport, place_shipment

__all__ = [
    "tensor_mesh",
    "shard_params",
    "cache_state_shardings",
    "sharded_engine",
    "PodConfig",
    "PodRouter",
    "PodEngine",
    "KVPageShipment",
    "PageTransport",
    "place_shipment",
]


def __getattr__(name):
    # layer 3 is import-heavy (sockets/threads) and optional for layer
    # 1/2 users — load it lazily on first touch
    if name in ("DistributedPodConfig", "DistributedPodRouter",
                "WorkerHandle", "build_local_distributed_pod"):
        from . import distributed

        return getattr(distributed, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
