"""Pod layer 1 (SPMD): tensor-parallel serving over a device mesh.

One engine, many chips: the family forward and the paged KV pool run
under `NamedSharding` on a mesh with a single `"model"` axis, so
admit/prefill/decode execute tensor-parallel over ICI (the pjit/TPUv4
static-shapes recipe, arxiv 2204.06514) while the engine's host-side
machinery — scheduler, paged allocator, prefix radix tree, page tables —
is untouched: page indices are *data*, and data doesn't care how the
arrays holding it are laid out across chips.

Division of labor:

- params: `shard_params` plans each leaf with the repo's path-pattern
  rules (`sharding/rules.py` — the Megatron column/row layout the
  `match_partition_rules` pattern encodes) and places it;
- KV pool: sharded over the KV-heads dim when the head count divides the
  mesh axis (each chip holds its heads' pages — attention is
  head-parallel, so the pool never moves), replicated otherwise (GQA
  models whose few KV heads don't divide; correct, just not
  memory-scaled — `cache_state_shardings` is the one place that policy
  lives);
- per-slot state (tokens/keys/temps/lengths): replicated — a few dozen
  scalars per slot.

The engine pins these layouts as its programs' `out_shardings`
(`EngineConfig.mesh`): GSPMD would otherwise be free to choose a
different output sharding than the input's, and since an array's sharding
is part of the jit cache key, the cache layout would drift compile by
compile instead of hitting a fixed point — the compile-count-flat
discipline extends to "flat per mesh", not just "flat per shape".

Everything here runs identically on a real slice and on the forced-host
CPU mesh (`XLA_FLAGS=--xla_force_host_platform_device_count=N`) the
tier-1 tests use, where token-exactness against the single-device engine
is proven byte for byte.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...sharding.planner import plan_sharding, shard_pytree
from ...sharding.rules import ShardingRules, transformer_rules
from ...utils.constants import AXIS_MODEL

__all__ = [
    "tensor_mesh",
    "shard_params",
    "cache_state_shardings",
    "sharded_engine",
]


def tensor_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D `("model",)` mesh over the first `num_devices` visible
    devices (None = all). The single axis is deliberate: serving decode
    is latency-bound, and tensor parallelism over ICI is the axis that
    cuts per-token latency — data/fsdp axes belong to training."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices) if num_devices is None else int(num_devices)
    if n < 1 or n > len(devices):
        raise ValueError(
            f"num_devices={n} out of range (1..{len(devices)} visible)")
    return Mesh(np.array(devices[:n]), (AXIS_MODEL,))


def shard_params(params: Any, mesh: Mesh,
                 rules: ShardingRules | None = None) -> Any:
    """Place a family's params on the mesh under the transformer rule set
    (column-parallel qkv/up projections, row-parallel out/down — the
    Megatron TP layout as path-pattern specs). Axes absent from the mesh
    prune away, so the same call serves a `("model",)` serving mesh and a
    richer training mesh."""
    rules = rules if rules is not None else transformer_rules()
    return shard_pytree(params, plan_sharding(params, mesh, rules))


def cache_state_shardings(cache, mesh: Mesh):
    """(cache_shardings, replicated) for an engine's pool + slot state.

    The pool shards over the KV-heads dim (axis 3 of
    [L, pages+1, page_size, H, D]) when H divides the model axis — each
    chip owns its heads' pages outright, page gathers/scatters stay
    chip-local, and pool HBM scales 1/N. When H doesn't divide (tiny-GQA
    models on a wide mesh) the pool falls back to sharding over the PAGE
    dim (axis 1) when the page count (+1 trash page) divides the axis —
    each chip owns a stripe of whole pages, so pool HBM still scales 1/N
    and a big pool never replicates per chip; the per-step page gathers
    then cross chips (GSPMD inserts the movement), trading bandwidth for
    memory. Only when NEITHER dim divides does the pool replicate:
    correct, latency still scales with the sharded matmuls, memory
    doesn't — size `num_pages` so pages+1 divides the mesh if the head
    count can't. int8 pools shard their scale arrays identically (same
    leading dims).

    The specs deliberately omit trailing `None` entries
    (`P(None, None, None, "model")`, not `...,"model", None)`): GSPMD
    normalizes specs that way in its output shardings, and the engine
    pins outputs to exactly these objects — a cosmetically different
    spelling of the same sharding would still be a different jit cache
    key on the next step's inputs."""
    n = mesh.shape[AXIS_MODEL]
    rep = NamedSharding(mesh, PartitionSpec())
    num_heads = cache.k.shape[3]
    # one spec serves pool and scales in every branch: the sharded dim
    # (heads = axis 3, pages = axis 1) sits at the same index in the 5-D
    # pool and the 4-D scale array
    if num_heads % n == 0:
        kv = NamedSharding(mesh, PartitionSpec(None, None, None, AXIS_MODEL))
    elif cache.k.shape[1] % n == 0:
        kv = NamedSharding(mesh, PartitionSpec(None, AXIS_MODEL))
    else:
        kv = rep
    cache_sh = dataclasses.replace(
        cache, k=kv, v=kv, lengths=rep,
        k_scale=kv if cache.quantized else None,
        v_scale=kv if cache.quantized else None)
    return cache_sh, rep


def sharded_engine(family, config, params, engine_config=None,
                   mesh: Mesh | None = None,
                   tensor_parallel: int | None = None,
                   rules: ShardingRules | None = None, **engine_kwargs):
    """The layer-1 factory: params sharded by rule, engine built with
    `EngineConfig(mesh=...)` so its pool/state are placed and its
    programs' out_shardings pinned. `tensor_parallel=N` builds the mesh
    over the first N visible devices; pass `mesh` to control placement.
    Returns the ordinary `Engine` — submit/stream/cancel, the scheduler,
    prefix reuse, telemetry, and strict-mode audits (now against
    `pod_program_contracts`) all work unchanged."""
    from ..engine import Engine, EngineConfig

    if mesh is None:
        mesh = tensor_mesh(tensor_parallel)
    ec = engine_config or EngineConfig()
    ec = dataclasses.replace(ec, mesh=mesh)
    placed = shard_params(params, mesh, rules)
    return Engine(family, config, placed, ec, **engine_kwargs)
