"""Continuous-batching serving engine: many requests, ONE compiled decode.

The inference surface this replaces is one blocking `generate()` per
request (`models/decode.py`): batch fixed at call time, every sequence at
the same depth, no cross-request multiplexing. The engine instead drives
exactly three compiled programs for its whole lifetime, whatever the
request mix:

- `admit`:   set a slot's length to the reused prefix length (0 on a cold
             miss), install the request's PRNG key and temperature (slot
             index is traced — one program for any slot);
- `prefill`: one fixed-size prompt chunk into one slot (prompts pad to the
             chunk, lengths advance by real tokens only — serving/cache.py);
- `decode`:  one token for EVERY slot, the family `forward` vmapped over
             slots with per-slot lengths/positions. Retired or prefilling
             slots ride along as masked lanes — fixed shapes are the price
             of never recompiling, and their lanes are reused the moment a
             queued request lands.

The KV store behind all three is a PAGED pool (`serving/cache.py
PagedKVCache`): each slot maps an ordered list of fixed-size pages
instead of a contiguous stripe, and the programs gather the slot's pages
into the familiar contiguous view / scatter the update back. Page tables
are host-side numpy ([slots, pages_per_slot] int32, padded with the
reserved trash page) passed to each dispatch as traced data — hit/miss
mixes, evictions, and remapping never change a program shape, so the
compile count stays flat at three. The host-side `PrefixIndex` +
`PagedAllocator` give cross-request prefix reuse: at admission the
longest cached prompt prefix is matched in a radix tree and those pages
are mapped copy-on-write (refcounted, full pages only — never written
again), so prefill runs ONLY on the uncached suffix; at retirement the
request's full prompt pages are released back into the tree instead of
wiped. Under shared-prefix traffic (system prompts, few-shot headers)
this removes the dominant prefill FLOPs and the TTFT they cost.

Sampling is per-slot: each request's PRNG key is installed at admit and
the step key derives as `fold_in(request_key, position)`, so streams never
correlate across slots and a request's sample sequence is independent of
how prefills/decodes interleave. Temperature is a traced per-slot scalar
(greedy and sampled requests share the same program).

Token delivery reuses the streamed-generate host plumbing: every decode
step ends in one small device->host read of the [S] token vector (the same
role the per-layer device->host probe plays in
`big_modeling.stream_layers`), which is what `stream()`/`astream()` yield
from.

Speculative decoding (`EngineConfig(speculative=(family, config, params),
draft_k=K)`, off by default — the three-program contract above is
unchanged when off) replaces the one-token decode step with a
draft/verify pair: a small family member drafts K tokens per slot (K
sequential steps of the cheap model against its own dense slot cache),
the target verifies all K in ONE batched K-token paged forward, and the
standard accept rule commits the agreed prefix plus one correction token
— exact-match for greedy (byte-identical output by construction),
rejection sampling for sampled requests (the committed distribution IS
the target's). Five fixed-shape programs (admit/prefill/draft_prefill/
draft/verify), each compiled once: per-slot accept counts are traced
data, so the compile count stays flat whatever the accept pattern.

Both decode flavors emit per-token LOGPROBS (log-softmax of the raw
target logits at the emitted token): the accept rule needs target
probabilities anyway, and the handle's `logprobs` list is what lets the
HTTP door return OpenAI `logprobs` and rank `best_of` by true cumulative
logprob. `fork()` clones a request COW-style: the parent's full prompt
pages are published into the radix tree as prefill completes them, so an
n-way fan-out pays ONE prompt prefill and siblings diverge at their
first private page.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from functools import partial
from typing import Any, AsyncIterator, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import sample_token
from ..profiler import StepTimer, causal_lm_infer_flops
from ..telemetry.cost import CostTable, resolve_sample_every
from ..telemetry.export import start_metrics_server
from ..telemetry.registry import MetricsRegistry
from ..telemetry.trace import (
    head_sample,
    new_trace_id,
    next_span_id,
    record_span,
    span,
    tracing_enabled,
)
from ..telemetry.watchdog import StallWatchdog, resolve_stall_timeout
from .cache import (
    PagedAllocator,
    PagedKVCache,
    SlotKVCache,
    paged_admit_slot,
    paged_append_batch,
    paged_append_rows,
    paged_append_window,
    paged_batch_view,
    paged_slot_view,
    paged_write_slot,
    slot_caches,
    write_slot,
)
from .metrics import ServingMetrics
from .sanitizer import SanitizerViolation, check_engine, resolve_sanitize
from .scheduler import Request, Scheduler, Slot, SlotState

__all__ = ["Engine", "EngineConfig"]


def prepare_request_tracing(req: Request, trace_id, trace_parent,
                            trace_sampled) -> None:
    """Install the request's trace identity at submit time — shared by
    `Engine.submit` and the pod router's front door so a request is traced
    identically whether one engine or a worker fleet serves it. The id is
    minted whenever tracing is on, sampled or not (request-id plumbing
    must not depend on the sampling rate); a sampled request pre-allocates
    its root span id so children can parent onto it before the root closes
    at the terminal state."""
    req.trace_id = trace_id
    req.trace_parent = trace_parent
    if trace_sampled is None:
        req.trace_sampled = head_sample(req.tenant)
    else:
        req.trace_sampled = bool(trace_sampled) and tracing_enabled()
    if req.trace_id is None and tracing_enabled():
        req.trace_id = new_trace_id()
    if req.trace_sampled:
        req.span_id = next_span_id()


def close_request_trace(req: Request, end: float) -> None:
    """Close a terminal request's retrospective spans: the decode-lifetime
    child (first token -> terminal) and the root `serving.request` span
    carrying status/reason/shed_code. EVERY terminal path must land here
    exactly once — finished, cancelled, rejected, shed — whether the
    request died in an engine or at the pod router before any engine saw
    it."""
    if not req.trace_sampled:
        return
    if req.first_token_at is not None and end > req.first_token_at:
        # decode lifetime: first token -> terminal (prefill chunks
        # are their own child spans; this is the streaming tail)
        record_span("serving.decode_lifetime", req.first_token_at, end,
                    trace=req.trace_id, parent=req.span_id,
                    tokens=len(req.tokens))
    attrs: dict[str, Any] = {
        "request_id": req.request_id,
        "tenant": req.tenant,
        "status": req.status.value,
        "prompt_len": req.prompt_len,
        "tokens": len(req.tokens),
    }
    if req.ttft_s is not None:
        attrs["ttft_s"] = req.ttft_s
    if req.reject_reason is not None:
        attrs["reason"] = req.reject_reason
    if req.shed_code is not None:
        attrs["shed_code"] = req.shed_code
    if req.parent_id is not None:
        # fork parentage rides the root span: a COW fan-out's siblings
        # all name the request whose prompt pages they share
        attrs["forked_from"] = req.parent_id
    record_span("serving.request", req.submitted_at, end,
                trace=req.trace_id, parent=req.trace_parent,
                span_id=req.span_id, **attrs)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving knobs. `max_len` bounds prompt+generated per slot (admission
    rejects longer requests); `prefill_chunk` trades prefill efficiency
    against how long a long prompt may stall decode (one chunk).

    Observability: `metrics_port` serves the engine's telemetry registry
    as a Prometheus endpoint from a background thread (0 = ephemeral
    port, read it from `engine.metrics_server.port`; None defers to
    `ACCELERATE_TPU_METRICS_PORT`, unset = off). `watchdog_timeout_s`
    arms a stall watchdog ticked by `step()` — after that much silence it
    dumps all-thread stacks / HBM stats / the span flight recorder to the
    log (None defers to `ACCELERATE_TPU_STALL_TIMEOUT_S`, unset = off)."""

    num_slots: int = 4
    max_len: int = 512
    prefill_chunk: int = 32
    max_queue: int = 64
    cache_dtype: Any = jnp.bfloat16
    seed: int = 0
    donate: bool = True
    # paged KV pool: per-request memory is allocated in `page_size`-token
    # pages at admission, and prompt prefixes already cached (full pages
    # of an earlier request's prompt) are mapped instead of recomputed.
    # `num_pages` sizes the pool (None = num_slots * pages_per_slot —
    # capacity parity with the old dense cache; MORE keeps retired
    # prefixes cached longer, LESS trades HBM for eviction churn).
    # `prefix_cache=False` keeps the paged layout but disables
    # cross-request reuse (every admission is a cold miss) — the A/B
    # baseline for the prefill-savings benchmark.
    page_size: int = 16
    num_pages: int | None = None
    prefix_cache: bool = True
    # hierarchical KV (ISSUE 16): byte budget for the host-DRAM overflow
    # tier (serving/host_tier.py). > 0 turns eviction from destruction
    # into demotion — refcount-0 prefixes falling out of the HBM pool
    # swap OUT to pinned host numpy (async, off the engine step), and a
    # later radix hit on a host-resident prefix swaps back IN through
    # the jitted PageTransport pair before admission, so the effective
    # prefix cache is host-memory-sized while compile counts stay flat.
    # 0 (default) = off, eviction destroys (the pre-ISSUE-16 behavior).
    # Sizing: capacity_pages = host_tier_bytes // cache.page_nbytes;
    # with kv_dtype="int8" each page is ~half the bf16 bytes, so the
    # same budget holds ~2x the prefix tokens.
    host_tier_bytes: int = 0
    # decode attention op. True: the Pallas paged-attention kernel
    # (ops/paged_attention.py) walks the page table INSIDE attention —
    # pages are read once, in place, only live pages per slot, GQA
    # broadcast in-kernel; one batched forward replaces the per-slot
    # vmap. False: the reference dense-gather path (paged_batch_view
    # before the vmapped forward — O(pool) reads per token). "auto"
    # picks the kernel on a single-device TPU and the dense path
    # elsewhere (on CPU the kernel runs in interpret mode — exact, and
    # what the tier-1 exactness tests drive explicitly, but far too slow
    # to default to; on a meshed engine the kernel is opaque to GSPMD,
    # which would gather the head-sharded pool around it — explicit True
    # there is an error). Either way the compile count stays flat at
    # admit/prefill/decode = 1/1/1.
    paged_attention: Any = "auto"
    # KV pool storage dtype. None stores pages in `cache_dtype`; "int8"
    # stores int8 codes + per-row-per-head bf16 scales (serving/cache.py)
    # — half the bytes per page, so a fixed HBM budget holds ~2x the
    # pages (= concurrent users). Both attention paths dequantize (the
    # kernel per page in VMEM, the dense path at gather); prefill/decode
    # writes quantize; pod shipments carry codes + scales, halving wire
    # bytes too. Accuracy is gated in tests by a logit-error bound and
    # greedy-token agreement.
    kv_dtype: Any = None
    # draft-model speculative decoding (ISSUE 12): a (family, config,
    # params) triple for a SMALL family member sharing the target's
    # vocabulary (the zoo's size-matched pairs — gpt2/gptj, llama
    # variants — or a distilled/truncated sibling). When set, decode
    # becomes draft-k-tokens + verify-in-one-batched-forward +
    # accept/fallback: greedy requests accept on exact match (output
    # byte-identical to the non-speculative engine), sampled requests
    # run standard rejection sampling (the committed distribution is
    # exactly the target's). None (default) keeps the classic one-token
    # decode and the exact three-program contract. Not supported on a
    # meshed engine or with paged_attention=True (the kernel is a
    # single-token op; "auto" resolves to the dense verify path).
    speculative: Any = None
    # tokens the draft proposes per speculative step (>= 1). Accepted
    # tokens per step range [1, draft_k]; raise it when the draft agrees
    # often (accept rate stays high), lower it when disagreement makes
    # late proposals worthless. docs/serving.md covers tuning.
    draft_k: int = 4
    # multi-tenant scheduling: an iterable/dict of scheduler.TenantSpec
    # (priority tiers, DRR weights, TTFT SLOs). None = the single
    # "default" tenant, i.e. plain FIFO — the pre-tenancy behavior.
    # All of it is host-side policy: the three compiled programs are
    # identical with or without tenants.
    tenants: Any = None
    metrics_port: int | None = None
    watchdog_timeout_s: float | None = None
    # device-cost attribution (ISSUE 11): every Kth call of each engine
    # program pays a block_until_ready fence pair so its TRUE device
    # duration lands in program_device_time_seconds{program=...}; with
    # the static cost table (FLOPs/bytes captured once per compiled
    # program) that yields live decode MFU / HBM-bandwidth utilization /
    # MXU-idle and the goodput number in metrics_summary(). Host-side
    # only — programs and compile counts are untouched. None defers to
    # ACCELERATE_TPU_COST_SAMPLE_EVERY (default 16); 0 disables
    # sampling (the static table still captures).
    cost_sample_every: int | None = None
    # incident bundles: when the stall watchdog fires (or the server's
    # drive loop dies), a self-contained bundle directory — metrics
    # snapshot, flight-recorder chrome trace, scheduler/allocator dumps,
    # all-thread stacks, device memory stats — lands here for
    # `accelerate-tpu incident list/show`. None defers to
    # ACCELERATE_TPU_INCIDENT_DIR; unset = log-only stall reports.
    incident_dir: str | None = None
    # strict="warn"|"error" audits each engine program ONCE, at its first
    # use: a mesh-placement check on the argument arrays (params leaked
    # onto a multi-device mesh -> ATP101, caught at the placement, since
    # GSPMD-inserted collectives don't exist yet in the lowering) plus the
    # lowered (pre-XLA, tracing cost only) program text: host-transfer
    # scan (ATP102) and the program's CollectiveContract over explicit
    # collectives (a psum snuck into the family forward). `contracts`
    # maps program name ("admit"/"prefill"/"decode") to an
    # analysis.CollectiveContract; None = the single-host default (NO
    # collectives, exhaustively) — or, when `mesh` is set, the
    # tensor-parallel `analysis.contracts.pod_program_contracts()` (the
    # sharded programs MUST carry the TP collectives; see below).
    # Findings land in the engine registry as
    # analysis_findings_total{rule=...}.
    strict: str | None = None
    contracts: Any = None
    # serving-state sanitizer (the runtime half of the ATP2xx lifecycle
    # audit, serving/sanitizer.py): after every engine step validate the
    # cross-structure invariants static analysis can't see — page
    # conservation across free list / radix tree / slot allocations,
    # refcounts vs live mappings (downward-closed along root paths),
    # device page-table discipline, length bounds, scheduler books.
    # Host-side only: programs and compile counts are untouched (pinned
    # by test). A violation raises SanitizerViolation with the broken
    # invariant named, after writing an incident bundle when
    # `incident_dir` is configured. None defers to the
    # ACCELERATE_TPU_SANITIZE env var (the test suite turns it on for
    # every tier-1 engine); default off in production — the checks walk
    # the whole tree each step.
    sanitize: Any = None
    # SPMD serving (serving/pod layer 1): a `jax.sharding.Mesh` with a
    # "model" axis. The engine then places its KV pool (sharded over KV
    # heads when they divide the axis, replicated otherwise) and its
    # per-slot state (replicated) on the mesh, and pins each program's
    # out_shardings to the same layout — without the pin GSPMD is free to
    # pick a different output sharding each step and the cache's sharding
    # (part of the jit cache key) never reaches a fixed point, so the
    # compile count creeps instead of staying flat at three. Params must
    # be mesh-placed by the caller (`serving.pod.shard_params`, or the
    # `serving.pod.sharded_engine` factory that does all of this).
    # strict-mode audits switch to the COMPILED program text (GSPMD
    # inserts the TP collectives after lowering), which costs one extra
    # XLA compile per program at first use.
    mesh: Any = None


def _cache_spec(config) -> tuple[int, int, int]:
    """(num_layers, num_kv_heads, head_dim) from any family config: GQA
    families carry num_key_value_heads, MHA families fall back to
    num_attention_heads."""
    kv = getattr(config, "num_key_value_heads", None)
    if kv is None:
        kv = config.num_attention_heads
    return config.num_hidden_layers, kv, config.head_dim


def _resolve_paged_attention(setting, mesh, speculative=None) -> bool:
    """EngineConfig.paged_attention -> use-the-kernel bool (see the
    config field's comment for the policy)."""
    if setting == "auto":
        return (mesh is None and speculative is None
                and jax.devices()[0].platform == "tpu")
    use = bool(setting)
    if use and mesh is not None:
        raise ValueError(
            "paged_attention=True is not supported on a meshed engine: a "
            "pallas kernel is opaque to GSPMD, which would gather the "
            "head-sharded pool around it instead of partitioning the "
            "kernel. Meshed engines keep the dense-gather decode path "
            "('auto' resolves to False there); single-device pod decode "
            "workers (tensor_parallel=1) can use the kernel.")
    if use and speculative is not None:
        raise ValueError(
            "paged_attention=True is not supported with speculative "
            "decoding: the Pallas kernel folds exactly ONE new token's "
            "K/V as its final online-softmax update, but the verify step "
            "is a draft_k-token forward. Leave paged_attention='auto' "
            "(the speculative verify uses the dense-gather path).")
    return use


def _as_raw_key(key) -> jax.Array:
    """uint32[2] key data from a typed key, raw key, or None."""
    if key is None:
        return None
    if (hasattr(key, "dtype")
            and jnp.issubdtype(key.dtype, jax.dtypes.prng_key)):
        return jax.random.key_data(key)
    return jnp.asarray(key, jnp.uint32)


class Engine:
    """Front-end: `submit()` -> request handle, `stream()`/`astream()` for
    tokens as they land, `cancel()`, `step()`/`run_until_idle()` to drive.

    `family` is any model-zoo module following the uniform decode contract
    (`forward(config, params, ids, positions=..., kv_caches=...) ->
    (logits, new_caches)` — see models/decode.py), or that forward callable
    directly.
    """

    def __init__(
        self,
        family,
        config,
        params,
        engine_config: EngineConfig | None = None,
        tracker=None,
        log_every: int = 0,
        clock=time.monotonic,
    ):
        self.config = config
        self.params = params
        self.engine_config = ec = engine_config or EngineConfig()
        if ec.mesh is not None and getattr(ec.mesh, "size", 1) <= 1:
            # a 1-device "mesh" IS single-device serving: there are no
            # collectives to contract-pin and no layouts to hold at a
            # fixed point — normalizing it away here keeps
            # `sharded_engine(..., tensor_parallel=1)` (and a 1-device
            # host) on the ordinary single-device path instead of
            # tripping the meshed strict audit, which demands sharded
            # args and TP reductions that can never exist on one chip
            self.engine_config = ec = dataclasses.replace(ec, mesh=None)
        self._forward = family if callable(family) else family.forward
        self._tracker = tracker
        self._log_every = log_every
        self._last_logged = 0
        self._clock = clock

        # validate config BEFORE any thread/port side effects below — a
        # bad value must not leak a bound metrics port or a live watchdog
        if ec.strict is not None and ec.strict not in ("warn", "error"):
            raise ValueError(
                f"strict must be None, 'warn', or 'error'; got {ec.strict!r}")
        self._spec = ec.speculative is not None
        if self._spec:
            if ec.mesh is not None:
                raise ValueError(
                    "speculative decoding is not supported on a meshed "
                    "engine yet: the draft would need its own placement "
                    "and the verify program its own pod contract — run "
                    "speculation on single-device engines (or pod decode "
                    "workers at tensor_parallel=1, speculative unset)")
            if ec.draft_k < 1:
                raise ValueError(f"draft_k must be >= 1, got {ec.draft_k}")
            try:
                dfam, dcfg, dparams = ec.speculative
            except (TypeError, ValueError):
                raise ValueError(
                    "speculative must be a (family, config, params) triple "
                    "for the draft model")
            if getattr(dcfg, "vocab_size", None) != config.vocab_size:
                raise ValueError(
                    f"draft vocab_size ({getattr(dcfg, 'vocab_size', None)})"
                    f" must match the target's ({config.vocab_size}): "
                    "drafted tokens are verified by id")
            self._draft_forward = dfam if callable(dfam) else dfam.forward
            self._draft_config = dcfg
            self._draft_params = dparams
        self._use_paged_kernel = _resolve_paged_attention(
            ec.paged_attention, ec.mesh, ec.speculative)
        self._contracts = ec.contracts
        if ec.strict is not None and self._contracts is None:
            if ec.mesh is not None:
                from ..analysis.contracts import pod_program_contracts

                self._contracts = pod_program_contracts(
                    num_layers=getattr(config, "num_hidden_layers", None))
            else:
                from ..analysis.contracts import serving_program_contracts

                self._contracts = serving_program_contracts(
                    paged_kernel=self._use_paged_kernel,
                    speculative=self._spec)
        # name -> None (audited clean/warned) | AnalysisViolation (cached:
        # re-raised on every later use without re-counting the findings)
        self._audited: dict = {}
        self._sanitize = resolve_sanitize(ec.sanitize)

        num_layers, num_kv, head_dim = _cache_spec(config)
        # pad_slack covers BOTH overshoot sources: chunk padding can spill
        # chunk-1 rows past max_len, and a speculative verify can write up
        # to draft_k candidate rows past the last budgeted token (the slot
        # retires mid-window; the extra rows land in reserved private
        # pages and are never attended)
        self._pad_slack = max(ec.prefill_chunk,
                              ec.draft_k if self._spec else 0)
        self.cache = PagedKVCache.create(
            num_layers, ec.num_slots, ec.max_len, num_kv, head_dim,
            dtype=ec.cache_dtype, page_size=ec.page_size,
            pad_slack=self._pad_slack, num_pages=ec.num_pages,
            kv_dtype=ec.kv_dtype,
        )
        if self._spec:
            dl, dkv, dhd = _cache_spec(self._draft_config)
            # the draft's own state is a DENSE slot cache (it is small,
            # and its K/V is a different model's — cached target pages
            # can never seed it, which is why prefix hits run draft-only
            # catch-up chunks)
            self._draft_cache = SlotKVCache.create(
                dl, ec.num_slots, ec.max_len, dkv, dhd,
                dtype=ec.cache_dtype, pad_slack=self._pad_slack)
        # SPMD serving: place the pool + per-slot state on the mesh and
        # remember the layout — _build_programs pins it as out_shardings
        # so every step's outputs land exactly where its inputs live (the
        # compile-count-flat fixed point; see EngineConfig.mesh)
        self._mesh_shardings = None
        if ec.mesh is not None:
            from .pod.mesh import cache_state_shardings

            self._mesh_shardings = cache_state_shardings(self.cache, ec.mesh)
            self.cache = jax.device_put(self.cache, self._mesh_shardings[0])
        # per-engine registry (not the process default) so concurrent
        # engines in one process never collide on series; the histograms
        # are streaming sketches, so a server that steps forever still
        # holds O(1) metric memory
        self.registry = MetricsRegistry()
        self.metrics = ServingMetrics(registry=self.registry)
        self.timer = StepTimer(warmup_steps=1, registry=self.registry,
                               name="serving_step")
        # per-program roofline attribution: static FLOPs/bytes captured
        # once per compiled program + sampled fence-pair device timing
        # (see EngineConfig.cost_sample_every)
        # num_chips matches the registration source: engine programs
        # register from the PRE-partition lowering (global FLOPs), so a
        # meshed engine's utilization divides by the whole mesh's peak;
        # a single-device engine is one chip however many the host shows
        self.cost = CostTable(registry=self.registry,
                              sample_every=resolve_sample_every(
                                  ec.cost_sample_every),
                              num_chips=(ec.mesh.size
                                         if ec.mesh is not None else 1))
        self._n_params: int | None = None  # resolved at first fallback
        # host-side page accounting: prefix radix tree + free list. The
        # lambdas read self.metrics at call time, so reset_metrics()'s
        # replacement instance keeps receiving events.
        self.allocator = PagedAllocator(
            page_size=ec.page_size,
            num_pages=self.cache.num_pages,
            pad_slack=self._pad_slack,
            prefix_cache=ec.prefix_cache,
            on_evict=lambda n: self.metrics.note_page_evictions(n),
            on_unmap=self._unmap_slot,
        )
        # COW forking: parent_id -> parent handle, consulted by the
        # admission hold below (entries drop as parents reach a terminal
        # state, so the map is bounded by live fan-outs)
        self._fork_parents: dict[int, Request] = {}
        # in-flight prefill dedup (ISSUE 16): request_ids currently held
        # behind a leader's prefill, so each follower counts exactly one
        # dedup hit however many steps it waits
        self._dedup_held: set[int] = set()
        if ec.prefix_cache:
            self.allocator.hold_admission = self._hold_admission
        # hierarchical KV: host-DRAM overflow tier + its jitted swap
        # transport (the pod PageTransport pair — extract on swap-out,
        # install on swap-in — compiles once each, so swap mixes never
        # move the compile count)
        self._host_tier = None
        self._swap_transport = None
        if ec.host_tier_bytes > 0:
            from .host_tier import HostTier
            from .pod.transfer import PageTransport

            self._swap_transport = PageTransport(self)
            self._host_tier = HostTier(self, ec.host_tier_bytes)
            self.allocator.swap_out = self._host_tier.offer
            self.allocator.swap_stall = self._host_tier.would_stall
            self.allocator.index.drop_host = self._host_tier.discard
        self.scheduler = Scheduler(ec.num_slots, ec.max_len,
                                   max_queue=ec.max_queue, clock=clock,
                                   allocator=self.allocator,
                                   tenants=ec.tenants,
                                   prefill_chunk=ec.prefill_chunk)
        # host-side page tables, one row per slot, padded with the trash
        # page: idle/retired lanes gather (and dead-write) only trash
        self._table = np.full(
            (ec.num_slots, self.cache.pages_per_slot),
            self.cache.trash_page, np.int32)
        # opt-in observability: Prometheus endpoint + stall watchdog
        self.metrics_server = start_metrics_server(
            ec.metrics_port, registry=self.registry)
        self.watchdog: StallWatchdog | None = None
        wd_timeout = resolve_stall_timeout(ec.watchdog_timeout_s)
        if wd_timeout is not None:
            self.watchdog = StallWatchdog(
                wd_timeout, name="serving-engine",
                incident_dir=ec.incident_dir, registry=self.registry,
                dumps=self.incident_dumps).start()

        self._tokens = jnp.zeros((ec.num_slots,), jnp.int32)
        self._slot_keys = jax.random.key_data(
            jax.random.split(jax.random.key(ec.seed), ec.num_slots))
        self._temps = jnp.zeros((ec.num_slots,), jnp.float32)
        if self._mesh_shardings is not None:
            rep = self._mesh_shardings[1]
            self._tokens = jax.device_put(self._tokens, rep)
            self._slot_keys = jax.device_put(self._slot_keys, rep)
            self._temps = jax.device_put(self._temps, rep)
        self._base_key = jax.random.key(ec.seed)
        # admission hook: called as on_admit(slot, request) at the END of
        # every admission, after the slot's page table and device state
        # are installed. First-class (like PagedAllocator's on_evict/
        # on_unmap) because external control planes — the pod router —
        # must observe the page allocation the instant it exists: a short
        # prompt can admit, prefill, and retire inside ONE step(), and
        # the allocation dies with the slot.
        self.on_admit: Any = None
        self._build_programs()

    # -- compiled programs ---------------------------------------------------

    def _build_programs(self) -> None:
        forward, config = self._forward, self.config
        chunk = self.engine_config.prefill_chunk
        # donation keeps the (large) cache update in place instead of
        # copying it every step; (1, 2) = cache, tokens in both programs
        don = (1, 2) if self.engine_config.donate else ()
        don_admit = (0, 1, 2) if self.engine_config.donate else ()
        # meshed engines pin output shardings to the input layout so the
        # jit cache key reaches its fixed point on the FIRST compile
        # (inputs are placed to exactly these shardings in __init__)
        admit_out = step_out = None
        if self._mesh_shardings is not None:
            cache_sh, rep = self._mesh_shardings
            admit_out = (cache_sh, rep, rep)
            step_out = (cache_sh, rep, rep)  # cache, tokens, logprobs

        def sample_slot(logits, key_raw, position, temp):
            """One slot's next token from [V] logits: traced temperature
            selects greedy vs sampled, the step key derives from the
            request key and the token's position (deterministic under any
            prefill/decode interleave). Also returns the token's logprob
            under the UNSCALED model distribution (temperature-free, so
            greedy and sampled scores are comparable — the best_of
            ranking currency)."""
            key = jax.random.fold_in(jax.random.wrap_key_data(key_raw),
                                     position)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temp, 1e-6)
            sampled = sample_token(scaled[None, None, :], key, 1.0)[0]
            tok = jnp.where(temp > 0.0, sampled.astype(jnp.int32), greedy)
            lp = jax.nn.log_softmax(logits)[tok]
            return tok, lp

        if self._spec:
            @partial(jax.jit, donate_argnums=don_admit + ((3,) if don_admit
                                                          else ()),
                     out_shardings=None)
            def admit(cache, slot_keys, temps, dlengths, slot, key_raw,
                      temp, reused_len):
                # a prefix hit starts the TARGET slot's length at the
                # reused prefix; the draft always starts cold (its K/V is
                # a different model's — catch-up chunks rebuild it)
                cache = paged_admit_slot(cache, slot, reused_len)
                slot_keys = slot_keys.at[slot].set(key_raw)
                temps = temps.at[slot].set(temp)
                dlengths = dlengths.at[slot].set(0)
                return cache, slot_keys, temps, dlengths
        else:
            @partial(jax.jit, donate_argnums=don_admit,
                     out_shardings=admit_out)
            def admit(cache, slot_keys, temps, slot, key_raw, temp,
                      reused_len):
                # a prefix hit starts the slot's length at the reused
                # prefix (those pages already hold its K/V); a miss
                # starts at zero
                cache = paged_admit_slot(cache, slot, reused_len)
                slot_keys = slot_keys.at[slot].set(key_raw)
                temps = temps.at[slot].set(temp)
                return cache, slot_keys, temps

        @partial(jax.jit, donate_argnums=don, out_shardings=step_out)
        def prefill(params, cache, tokens, slot_keys, temps, slot,
                    table_row, ids, real_len):
            ks, vs, length = paged_slot_view(cache, table_row, slot)
            positions = (length + jnp.arange(chunk, dtype=jnp.int32))[None, :]
            logits, (nk, nv, _) = forward(
                config, params, ids[None, :], positions=positions,
                kv_caches=(ks, vs, length),
            )
            cache = paged_write_slot(cache, table_row, slot, nk, nv, real_len,
                                     chunk)
            new_len = length + real_len
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), real_len - 1, keepdims=False)
            tok, lp = sample_slot(last, slot_keys[slot], new_len, temps[slot])
            tokens = tokens.at[slot].set(tok)
            return cache, tokens, lp

        decode = None
        if self._spec:
            pass  # draft/verify replace the one-token decode below
        elif self._use_paged_kernel:
            from ..ops.paged_attention import PagedDecodeMeta, PagedKV

            rows = self.cache.rows

            @partial(jax.jit, donate_argnums=don, out_shardings=step_out)
            def decode(params, cache, tokens, slot_keys, temps, live, table):
                # the Pallas kernel walks the page table INSIDE attention:
                # no gather, no per-slot vmap — one batched forward whose
                # cache-attend step (models/decode.decode_attention)
                # streams each slot's live pages through VMEM in place and
                # hands back only the per-slot new K/V rows to scatter
                kvc = (PagedKV(cache.k, cache.k_scale, cache.compute_dtype),
                       PagedKV(cache.v, cache.v_scale, cache.compute_dtype),
                       PagedDecodeMeta(table, cache.lengths, rows=rows))
                logits, (row_k, row_v, _) = forward(
                    config, params, tokens[:, None],
                    positions=cache.lengths[:, None], kv_caches=kvc,
                )
                last = logits[:, 0].astype(jnp.float32)
                next_tok, lps = jax.vmap(sample_slot)(
                    last, slot_keys, cache.lengths + 1, temps)
                tokens = jnp.where(live, next_tok, tokens)
                cache = paged_append_rows(cache, table, row_k[:, :, 0],
                                          row_v[:, :, 0], live)
                return cache, tokens, lps
        else:
            @partial(jax.jit, donate_argnums=don, out_shardings=step_out)
            def decode(params, cache, tokens, slot_keys, temps, live, table):
                # the dense-gather reference path: one [L, S, R, H, D]
                # view of every slot's pages gathered OUTSIDE the vmap,
                # exactly the layout the family forward already vmaps
                # over; the per-page indices are traced data
                k_all, v_all = paged_batch_view(cache, table)

                def single(tok, length, k_slot, v_slot):
                    logits, (nk, nv, _) = forward(
                        config, params, tok[None, None],
                        positions=length[None, None],
                        kv_caches=(k_slot[:, None], v_slot[:, None], length),
                    )
                    return (logits[0, 0].astype(jnp.float32), nk[:, 0],
                            nv[:, 0])

                last, nk, nv = jax.vmap(
                    single, in_axes=(0, 0, 1, 1), out_axes=(0, 1, 1)
                )(tokens, cache.lengths, k_all, v_all)
                next_tok, lps = jax.vmap(sample_slot)(
                    last, slot_keys, cache.lengths + 1, temps)
                tokens = jnp.where(live, next_tok, tokens)
                cache = paged_append_batch(cache, table, nk, nv, live)
                return cache, tokens, lps

        self._admit_p, self._prefill_p, self._decode_p = admit, prefill, decode
        if self._spec:
            self._build_speculative_programs(sample_slot)

    def _build_speculative_programs(self, sample_slot) -> None:
        """The speculative replacement for the decode step, as fixed-shape
        programs (ISSUE 12):

        - `draft_prefill`: one chunk of the DRAFT model's prompt prefill
          into its dense slot cache (the draft re-reads the whole prompt,
          including any target-side reused prefix — cached pages hold the
          TARGET's K/V, which can't seed a different model);
        - `draft`: K sequential one-token steps of the draft, scanned
          inside one program — proposals + the draft's full logits ride
          out for the accept rule;
        - `verify`: ONE batched K-token target forward over every slot's
          paged view (exactly PR 10's short-sequence paged forward), the
          accept rule, and the fixed-shape commit (accepted rows scatter
          to their pages, rejected rows to trash — per-slot counts are
          traced data, so accept patterns never change a shape).

        Sampling keys: token at absolute position p in the NON-speculative
        engine uses fold_in(request_key, p); the speculative step needs
        three independent draws per position (draft proposal, accept
        uniform, residual resample), derived as fold_in(fold_in(key, p),
        tag) with distinct tags — still slot-decorrelated and
        schedule-independent, and independent of each other, which is
        what the rejection-sampling correctness argument requires."""
        forward, config = self._forward, self.config
        dforward, dcfg = self._draft_forward, self._draft_config
        chunk = self.engine_config.prefill_chunk
        K = self.engine_config.draft_k
        S = self.engine_config.num_slots
        don = (1, 2) if self.engine_config.donate else ()
        don_d = (1,) if self.engine_config.donate else ()
        DRAFT_TAG, ACCEPT_TAG, RESID_TAG = 1, 2, 3

        @partial(jax.jit, donate_argnums=don_d)
        def draft_prefill(dparams, dcache, slot, ids, real_len):
            ks, vs, length = slot_caches(dcache, slot)
            positions = (length + jnp.arange(chunk, dtype=jnp.int32))[None, :]
            _, (nk, nv, _) = dforward(dcfg, dparams, ids[None, :],
                                      positions=positions,
                                      kv_caches=(ks, vs, length))
            return write_slot(dcache, slot, nk, nv, real_len)

        @partial(jax.jit, donate_argnums=don_d)
        def draft(dparams, dcache, tokens, slot_keys, temps):
            def single(tok, length, ks, vs):
                logits, (nk, nv, _) = dforward(
                    dcfg, dparams, tok[None, None],
                    positions=length[None, None],
                    kv_caches=(ks[:, None], vs[:, None], length))
                return logits[0, 0].astype(jnp.float32), nk[:, 0], nv[:, 0]

            def propose(lg, key_raw, pos, temp):
                key = jax.random.fold_in(
                    jax.random.fold_in(jax.random.wrap_key_data(key_raw),
                                       pos), DRAFT_TAG)
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                sampled = jax.random.categorical(
                    key, lg / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
                return jnp.where(temp > 0.0, sampled, greedy)

            def body(carry, _):
                tok, k_all, v_all, lengths = carry
                lg, nk, nv = jax.vmap(single, in_axes=(0, 0, 1, 1),
                                      out_axes=(0, 1, 1))(
                    tok, lengths, k_all, v_all)
                nxt = jax.vmap(propose)(lg, slot_keys, lengths + 1, temps)
                return (nxt, nk, nv, lengths + 1), (nxt, lg)

            (_, nk, nv, _), (d_toks, d_logits) = jax.lax.scan(
                body, (tokens, dcache.k, dcache.v, dcache.lengths),
                None, length=K)
            # scan stacks on a leading step dim: -> [S, K] / [S, K, V]
            return (d_toks.T, jnp.moveaxis(d_logits, 0, 1),
                    dataclasses.replace(dcache, k=nk, v=nv,
                                        lengths=dcache.lengths + K))

        @partial(jax.jit, donate_argnums=don)
        def verify(params, cache, tokens, slot_keys, temps, live, table,
                   d_toks, d_logits):
            # inputs per slot: [t0, d1..d_{K-1}] at positions L..L+K-1 —
            # row j's logits is the target distribution for the token at
            # position L+j+1, i.e. proposal d_{j+1}'s judge
            ids = jnp.concatenate([tokens[:, None], d_toks[:, :K - 1]],
                                  axis=1)
            k_all, v_all = paged_batch_view(cache, table)

            def single(ids_s, length, ks, vs):
                positions = (length
                             + jnp.arange(K, dtype=jnp.int32))[None, :]
                logits, (nk, nv, _) = forward(
                    config, params, ids_s[None, :], positions=positions,
                    kv_caches=(ks[:, None], vs[:, None], length))
                return logits[0].astype(jnp.float32), nk[:, 0], nv[:, 0]

            t_logits, nk, nv = jax.vmap(single, in_axes=(0, 0, 1, 1),
                                        out_axes=(0, 1, 1))(
                ids, cache.lengths, k_all, v_all)

            def accept_slot(tl, dl, dt, key_raw, base, temp):
                # tl/dl [K, V] target/draft logits; dt [K] proposals
                key = jax.random.wrap_key_data(key_raw)
                pos = base + 1 + jnp.arange(K, dtype=jnp.int32)
                greedy_ok = dt == jnp.argmax(tl, axis=-1).astype(jnp.int32)
                p = jax.nn.softmax(tl / jnp.maximum(temp, 1e-6), axis=-1)
                q = jax.nn.softmax(dl / jnp.maximum(temp, 1e-6), axis=-1)
                p_tok = jnp.take_along_axis(p, dt[:, None], axis=1)[:, 0]
                q_tok = jnp.take_along_axis(q, dt[:, None], axis=1)[:, 0]

                def u_at(po):
                    return jax.random.uniform(jax.random.fold_in(
                        jax.random.fold_in(key, po), ACCEPT_TAG))

                # accept d_i with prob min(1, p(d_i)/q(d_i)) — spelled
                # u*q < p so q=0 (a proposal the draft couldn't have
                # sampled) auto-rejects without a division
                samp_ok = jax.vmap(u_at)(pos) * q_tok < p_tok
                ok = jnp.where(temp > 0.0, samp_ok, greedy_ok)
                prefix = jnp.cumprod(ok.astype(jnp.int32))
                n_acc = prefix.sum()
                c = jnp.where(n_acc == K, K, n_acc + 1)
                # correction at the first rejected position: sample the
                # residual max(p - q, 0)/Z — together with the accepts
                # this reproduces the target distribution exactly
                r = jnp.minimum(n_acc, K - 1)
                resid = jnp.maximum(p[r] - q[r], 0.0)
                resid = jnp.where(resid.sum() > 1e-9, resid, p[r])
                rkey = jax.random.fold_in(
                    jax.random.fold_in(key, base + 1 + r), RESID_TAG)
                corr_sampled = jax.random.categorical(
                    rkey, jnp.log(resid + 1e-30)).astype(jnp.int32)
                corr_greedy = jnp.argmax(tl[r], axis=-1).astype(jnp.int32)
                corr = jnp.where(temp > 0.0, corr_sampled, corr_greedy)
                j = jnp.arange(K, dtype=jnp.int32)
                committed = jnp.where(j < n_acc, dt, corr)
                logp = jax.nn.log_softmax(tl, axis=-1)
                lps = jnp.take_along_axis(logp, committed[:, None],
                                          axis=1)[:, 0]
                return (committed, c.astype(jnp.int32),
                        n_acc.astype(jnp.int32), lps)

            committed, counts, n_acc, lps = jax.vmap(accept_slot)(
                t_logits, d_logits, d_toks, slot_keys, cache.lengths, temps)
            counts = jnp.where(live, counts, 0)
            n_acc = jnp.where(live, n_acc, 0)
            new_tok = committed[jnp.arange(S), jnp.maximum(counts, 1) - 1]
            tokens = jnp.where(live, new_tok, tokens)
            # keep exactly the accepted inputs' K/V rows (t0..d_{c-1});
            # rejected candidates' rows route to trash inside the
            # fixed-shape window scatter
            rows = cache.lengths[:, None] + jnp.arange(K, dtype=jnp.int32)
            idx = rows[None, :, :, None, None]
            win_k = jnp.take_along_axis(nk, idx, axis=2)
            win_v = jnp.take_along_axis(nv, idx, axis=2)
            cache = paged_append_window(cache, table, win_k, win_v,
                                        counts, live)
            return cache, tokens, committed, counts, n_acc, lps

        self._draft_prefill_p = draft_prefill
        self._draft_p = draft
        self._verify_p = verify

    def compile_stats(self) -> dict[str, int]:
        """Compiled-program counts per engine program — the recompile
        guard: these must stay flat however the request mix changes.
        Speculative engines report their five programs (the one-token
        decode is never built); classic engines keep the exact
        admit/prefill/decode triple."""
        out = {
            "admit": self._admit_p._cache_size(),
            "prefill": self._prefill_p._cache_size(),
        }
        if self._spec:
            out["draft_prefill"] = self._draft_prefill_p._cache_size()
            out["draft"] = self._draft_p._cache_size()
            out["verify"] = self._verify_p._cache_size()
        else:
            out["decode"] = self._decode_p._cache_size()
        if self._swap_transport is not None:
            # host tier on: the swap pair must stay flat too, whatever
            # the swap-out/swap-in mix (keys match the pod transport's)
            out.update(self._swap_transport.compile_stats())
        return out

    # -- request API ---------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        key=None,
        eos_token_id: int | None = None,
        deadline_s: float | None = None,
        tenant: str = "default",
        slo_ttft_s: float | None = None,
        trace_id=None,
        trace_parent=0,
        trace_sampled: bool | None = None,
        parent_id: int | None = None,
    ) -> Request:
        """Queue one generation request; returns its handle immediately.
        Overload is reported on the handle (`status` REJECTED with
        `reject_reason`, a machine-readable `shed_code`, and a
        `retry_after_s` backoff hint), never deferred to an OOM.
        `tenant` routes the request through that tenant's priority tier /
        DRR share; `slo_ttft_s` overrides the tenant's TTFT SLO for this
        request. `trace_id`/`trace_parent` join the request to an
        externally minted trace (the HTTP layer's, or an inbound W3C
        traceparent); with tracing enabled and no id supplied the engine
        mints one, so direct engine callers get request ids too.
        Whether SPANS record is the per-tenant head-sampling decision —
        made here unless the caller passes `trace_sampled` (the server
        decides ONCE per HTTP request so n/best_of siblings sample
        together; a half-sampled fan-out is noise). An unsampled request
        keeps its id (request-id plumbing must not depend on the
        sampling rate)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(temperature), key=key,
            eos_token_id=eos_token_id, deadline_s=deadline_s,
            tenant=tenant, slo_ttft_s=slo_ttft_s, parent_id=parent_id,
        )
        prepare_request_tracing(req, trace_id, trace_parent, trace_sampled)
        # drain first, THEN capacity-check: a slot freed since the last
        # step (or an expired entry still holding a queue position) must
        # make room before this request is judged against max_queue — the
        # queue bound covers genuinely *waiting* requests only
        self._admit_pending()
        self.scheduler.submit(req)
        # pressure/displacement victims shed INSIDE submit have no other
        # path into the metrics — drain them before reporting the newcomer
        for victim in self.scheduler.drain_shed():
            self._finalize_request(victim)
        if req.done:
            self._finalize_request(req)
        else:
            # eager admission: a free slot absorbs the request now, so
            # TTFT doesn't wait for the next step() call
            self._admit_pending()
        return req

    def fork(
        self,
        parent: Request,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        key=None,
        eos_token_id: Any = "inherit",
        deadline_s: float | None = None,
        slo_ttft_s: float | None = None,
        trace_id=None,
        trace_parent=0,
        trace_sampled: bool | None = None,
    ) -> Request:
        """COW-fork `parent`: a new request on the same prompt that
        SHARES the parent's prompt pages instead of re-prefilling them.

        Mechanism: the parent is marked `share_prompt`, which publishes
        its full prompt pages into the radix tree the moment prefill
        completes them (`PagedAllocator.publish_prompt` — mid-flight,
        not at retirement), plus immediately here for whatever is
        already prefilled. The fork is then an ordinary submission whose
        admission maps the published pages copy-on-write and diverges at
        its first private page — an n-way `n`/`best_of` fan-out pays ONE
        prompt prefill (each sibling still prefills the final partial
        page: the last prompt token must produce its own first-token
        logits). Works at any parent phase: queued (pages publish as
        they prefill), running, or finished (pages are in the tree
        already); a cancelled parent's published pages survive in the
        tree, so forks keep their sharing — the COW refcounts isolate
        every sibling. Unset generation knobs inherit the parent's;
        `key` should differ per fork or siblings sample identical
        streams (None derives a distinct key from the fork's request
        id). With `prefix_cache=False` the fork still runs, it just
        re-prefills — sharing needs the radix tree."""
        parent.share_prompt = True
        if not parent.done:
            self._fork_parents[parent.request_id] = parent
        for slot in self.scheduler.slots:
            if slot.request is parent:
                self.allocator.publish_prompt(slot)
                break
        return self.submit(
            parent.prompt,
            max_new_tokens=(parent.max_new_tokens if max_new_tokens is None
                            else max_new_tokens),
            temperature=(parent.temperature if temperature is None
                         else temperature),
            key=key,
            eos_token_id=(parent.eos_token_id if eos_token_id == "inherit"
                          else eos_token_id),
            deadline_s=deadline_s,
            tenant=parent.tenant,
            slo_ttft_s=slo_ttft_s,
            trace_id=trace_id,
            trace_parent=trace_parent,
            trace_sampled=trace_sampled,
            parent_id=parent.request_id,
        )

    def cancel(self, request: Request) -> bool:
        if self.scheduler.cancel(request):
            self._finalize_request(request)
            return True
        return False

    def finish(self, request: Request) -> bool:
        """Retire a running request as FINISHED before its budget (e.g.
        a server-side stop sequence matched): counts in the finished/
        latency metrics, prompt pages cached for reuse."""
        if self.scheduler.finish_early(request):
            self._finalize_request(request)
            return True
        return False

    def stream(self, request: Request) -> Iterator[int]:
        """Yield the request's tokens as the engine produces them, driving
        `step()` while the request is live."""
        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
        yield from request.tokens[sent:]

    async def astream(self, request: Request) -> AsyncIterator[int]:
        """`stream()` for asyncio callers: yields control to the loop
        between engine steps so concurrent coroutines interleave."""
        sent = 0
        while True:
            while sent < len(request.tokens):
                yield request.tokens[sent]
                sent += 1
            if request.done or not self.step():
                break
            await asyncio.sleep(0)
        for tok in request.tokens[sent:]:
            yield tok

    # -- the drive loop ------------------------------------------------------

    def step(self) -> bool:
        """Run one scheduler action (admissions + one prefill chunk OR one
        batched decode step). Returns False when the engine is idle."""
        if self.metrics.started_at is None:
            self.metrics.started_at = self._clock()
        if self.watchdog is not None:
            self.watchdog.tick()
        self._admit_pending()
        action = self.scheduler.next_action()
        if action is None:
            self.metrics.stopped_at = self._clock()
            if self._sanitize:
                self._sanity_check()
            return False
        t0 = self._clock()
        if action[0] == "prefill":
            self._run_prefill_chunk(action[1])
        else:
            self._run_decode(action[1])
        self.metrics.stopped_at = self._clock()
        # the EMA behind the scheduler's SLO / Retry-After estimates —
        # host-side bookkeeping only, nothing traced
        self.scheduler.note_step_time(self.metrics.stopped_at - t0)
        self.metrics.observe_step(self.scheduler.live_slots,
                                  self.engine_config.num_slots,
                                  self.scheduler.queue_depth)
        # keep the goodput gauge live for mid-run scrapes (a handful of
        # host float ops — the device never sees it)
        self._goodput()
        self._maybe_log()
        if self._sanitize:
            self._sanity_check()
        return True

    def _sanity_check(self) -> None:
        """Run the serving-state sanitizer (EngineConfig(sanitize=True)):
        cross-structure invariants after this step. On a violation the
        incident-bundle machinery captures the engine's debug state
        before the structured SanitizerViolation propagates."""
        try:
            check_engine(self)
        except SanitizerViolation as e:
            self._write_sanitizer_incident(e)
            raise

    def _write_sanitizer_incident(self, e: SanitizerViolation) -> None:
        from ..telemetry.watchdog import (
            build_exception_report,
            resolve_incident_dir,
            write_incident_bundle,
        )

        incident_dir = resolve_incident_dir(
            self.engine_config.incident_dir)
        if incident_dir is None:
            return
        try:
            report = build_exception_report(e, name="sanitizer")
            report["check"] = e.check
            report["details"] = e.details
            write_incident_bundle(
                incident_dir, report, registry=self.registry,
                dumps=self.incident_dumps(), name="sanitizer")
        except Exception:
            pass  # the violation itself must still propagate

    def run_until_idle(self) -> None:
        while self.step():
            pass

    def _admit_pending(self) -> None:
        """Shed expired/doomed queued requests, then admit from the
        queue into free slots. Observation goes through the scheduler's
        shed log — the one path that also covers victims shed inside
        submit() (queue-pressure and tier-displacement sheds)."""
        now = self._clock()
        self.scheduler.shed_expired(now)
        for req in self.scheduler.drain_shed():
            self._finalize_request(req)
        for slot, req in self.scheduler.admissions(now):
            self._run_admit(slot, req)

    def _strict_audit(self, name: str, jitted, args: tuple) -> None:
        """Strict-mode program passes, once per program, at first use.

        Two layers: (1) a direct mesh-placement check on the argument
        arrays. On a single-host engine an arg spanning >1 device means
        GSPMD will insert collectives at partitioning time, AFTER the
        lowering this audit reads — the 'params leaked onto a mesh'
        hazard, caught at the placement itself. On a MESHED engine
        (EngineConfig.mesh) the check inverts: a prefill/decode whose
        every argument is fully replicated (or single-device) means the
        params were never sharded — each device computes the whole model
        and tensor parallelism silently bought nothing. (2) the program
        text: single-host engines read the lowering (tracing cost only —
        shard_map-explicit collectives and host callbacks are visible
        there); meshed engines read the COMPILED optimized HLO (one extra
        XLA compile per program, once — the GSPMD-inserted TP collectives
        only exist there) and check it against the pod contract."""
        if self.engine_config.strict is None:
            return
        from ..analysis.findings import Finding, run_cached_audit
        from ..analysis.program import find_host_transfers

        pname = f"serving.{name}"
        on_mesh = self.engine_config.mesh is not None

        def audit():
            findings = []
            meshed = [
                leaf for leaf in jax.tree_util.tree_leaves(args)
                if isinstance(leaf, jax.Array)
                and len(leaf.sharding.device_set) > 1
            ]
            if meshed and not on_mesh:
                ndev = max(len(leaf.sharding.device_set) for leaf in meshed)
                findings.append(Finding(
                    rule="ATP101",
                    message=(
                        f"{len(meshed)} argument array(s) span {ndev} "
                        "devices: GSPMD inserts collectives after lowering, "
                        "invisible to this audit — a single-host engine "
                        "expects unplaced params (sharded-serving setups "
                        "must configure EngineConfig(mesh=...), which "
                        "audits compiled HLO against the pod contracts)"),
                    path=f"<program:{pname}>",
                    source=f"mesh-placed args x{len(meshed)}",
                ))
            if on_mesh and name in ("prefill", "decode") and not any(
                    isinstance(leaf, jax.Array)
                    and len(leaf.sharding.device_set) > 1
                    and not leaf.sharding.is_fully_replicated
                    for leaf in jax.tree_util.tree_leaves(args)):
                findings.append(Finding(
                    rule="ATP101",
                    message=(
                        "tensor-parallel engine with no sharded argument: "
                        "params were not mesh-placed (pass them through "
                        "serving.pod.shard_params, or use the "
                        "serving.pod.sharded_engine factory) — every "
                        "device is computing the full model"),
                    path=f"<program:{pname}>",
                    source="mesh engine, fully-replicated args",
                ))
            if on_mesh:
                # GSPMD collectives exist only post-partitioning: audit
                # the compiled text (one extra compile, cached audit)
                text = jitted.lower(*args).compile().as_text()
            else:
                text = jitted.lower(*args).as_text()
            findings += find_host_transfers(text, name=pname)
            contract = (self._contracts or {}).get(name)
            if contract is not None:
                findings += contract.check(text)
            return findings

        run_cached_audit(
            self._audited, name, self.engine_config.strict, audit,
            on_finding=lambda f: self.registry.counter(
                "analysis_findings_total", rule=f.rule).inc(),
            label=f"engine program {pname!r}",
        )

    def _ensure_cost(self, name: str, program, args: tuple) -> None:
        """Capture the program's static cost ONCE, at its first dispatch
        — `lower()` on the jitted program (tracing cost only, no extra
        XLA compile: the jit's own executable cache is what
        compile_stats() counts, and it is untouched). Backends that
        report no cost_analysis fall back to the analytic per-family
        estimate."""
        if self.cost.has(name):
            return
        try:
            src = program.lower(*args)
        except Exception:
            src = None
        self.cost.register(name, src,
                           fallback=lambda: self._analytic_cost(name))

    def _analytic_cost(self, name: str) -> tuple[float, float]:
        """Analytic fallback (flops, bytes) per program call when the
        backend reports nothing: ~2 FLOPs/param/token + the attention-
        over-cache term (profiler.causal_lm_infer_flops), bytes = one
        full weight read + the KV rows touched. The mid-stream context
        length is unknown statically; max_len/2 is the documented
        approximation."""
        cfg, ec = self.config, self.engine_config
        from ..models.common import count_params

        if name in ("draft", "draft_prefill"):
            cfg = self._draft_config
            if getattr(self, "_n_draft_params", None) is None:
                self._n_draft_params = count_params(self._draft_params)
            n = self._n_draft_params
        else:
            if self._n_params is None:
                self._n_params = count_params(self.params)
            n = self._n_params
        num_layers, num_kv, head_dim = _cache_spec(cfg)
        hidden = getattr(cfg, "hidden_size", 0) or (
            getattr(cfg, "num_attention_heads", 1) * head_dim)
        avg_ctx = max(1, ec.max_len // 2)
        elt = 2  # bf16 weights/activations
        kv_row = num_kv * head_dim * elt * 2  # one K row + one V row
        if name == "decode":
            tokens = ec.num_slots
            flops = causal_lm_infer_flops(n, tokens, num_layers, hidden,
                                          kv_len=avg_ctx)
            nbytes = n * elt + tokens * num_layers * avg_ctx * kv_row
        elif name == "verify":
            # one K-token forward per slot — the batched verify is
            # decode with draft_k tokens per lane
            tokens = ec.num_slots * ec.draft_k
            flops = causal_lm_infer_flops(n, tokens, num_layers, hidden,
                                          kv_len=avg_ctx)
            nbytes = (n * elt
                      + ec.num_slots * num_layers * avg_ctx * kv_row)
        elif name == "draft":
            # K sequential one-token draft steps over every slot
            tokens = ec.num_slots * ec.draft_k
            flops = causal_lm_infer_flops(n, tokens, num_layers, hidden,
                                          kv_len=avg_ctx)
            nbytes = ec.draft_k * n * elt \
                + tokens * num_layers * avg_ctx * kv_row
        elif name in ("prefill", "draft_prefill"):
            tokens = ec.prefill_chunk
            flops = causal_lm_infer_flops(n, tokens, num_layers, hidden,
                                          kv_len=avg_ctx)
            nbytes = (n * elt + tokens * num_layers * kv_row
                      + num_layers * avg_ctx * kv_row)
        else:  # admit: per-slot bookkeeping only, no model math
            flops, nbytes = 0.0, float(ec.num_slots * 16)
        return float(flops), float(nbytes)

    def _hold_fork_child(self, req: Request) -> bool:
        """Admission hold for COW forks: a fork child stays QUEUED until
        its parent's full prompt pages are published (or the parent is
        terminal — then whatever made it into the tree is all there will
        be). Admitting earlier would cold-prefill the shared prompt and
        forfeit the single-prefill property the fork exists for. Progress
        is guaranteed: a live parent's prefill advances every engine
        step, and a shed/cancelled parent releases the hold immediately."""
        if req.parent_id is None:
            return False
        parent = self._fork_parents.get(req.parent_id)
        if parent is None or parent.done:
            return False
        want = (req.prompt_len - 1) // self.engine_config.page_size
        if want <= 0:
            return False  # nothing shareable: sub-page prompts admit cold
        for slot in self.scheduler.slots:
            if slot.request is parent:
                have = min(slot.prompt_done, parent.prompt_len) \
                    // self.engine_config.page_size
                return have < want
        return True  # parent still queued: its prefill hasn't started

    def _hold_admission(self, req: Request) -> bool:
        """The allocator's admission-hold hook: COW fork children wait
        for their parent's publish (above), and — cache-aware scheduling,
        ISSUE 16 — any queued request whose full shareable prefix is
        currently being prefilled by another request waits for that
        leader's pages instead of duplicating the prefill."""
        return self._hold_fork_child(req) or self._hold_for_dedup(req)

    def _hold_for_dedup(self, req: Request) -> bool:
        """In-flight prefill dedup. If a PREFILL-state slot's prompt
        covers `req`'s full shareable prefix, flag that leader to
        publish its prompt pages mid-flight (`publish_prompt`, the COW
        fork machinery) and hold `req` until the published pages cover
        it — N concurrent identical prompts then cost ONE full prefill
        (each follower still prefills its private sub-page tail).

        Bounded by policy: a request never waits on a LOWER-priority
        tier's leader (a gold request never waits on a bronze leader),
        and the hold re-evaluates every admission attempt, so a leader
        that is cancelled, shed, or finished early simply stops
        matching and the follower re-prefills cold — waits are bounded
        by the leader's own prefill progress, which advances every
        step."""
        want = (req.prompt_len - 1) // self.engine_config.page_size
        if want <= 0:
            return False
        if len(self.allocator.index.match(req.prompt)) >= want:
            # the tree already covers us (HBM or host) — admit now
            self._dedup_held.discard(req.request_id)
            return False
        k = want * self.engine_config.page_size
        my_tier = self.scheduler.tenant_priority(req.tenant)
        head = req.prompt[:k]
        for slot in self.scheduler.slots:
            leader = slot.request
            if (slot.state is not SlotState.PREFILL or leader is None
                    or leader is req):
                continue
            if leader.prompt_len < k \
                    or self.scheduler.tenant_priority(leader.tenant) > my_tier:
                continue
            if not np.array_equal(np.asarray(leader.prompt[:k]), head):
                continue
            leader.share_prompt = True  # publish from the next chunk on
            if self.allocator.publish_prompt(slot) >= want:
                self._dedup_held.discard(req.request_id)
                return False
            if req.request_id not in self._dedup_held:
                self._dedup_held.add(req.request_id)
                self.metrics.note_dedup_hit()
            return True
        self._dedup_held.discard(req.request_id)
        return False

    def _unmap_slot(self, index: int) -> None:
        """Allocator callback at release: reset the slot's page table to
        all-trash BEFORE its pages can be reallocated, so the retired
        lane's masked ride-along writes in later decode steps can never
        land in a page now owned by someone else."""
        self._table[index, :] = self.cache.trash_page
        self.metrics.set_page_gauges(
            self.allocator.pages_in_use, self.allocator.pages_free,
            self.allocator.pages_in_use * self.cache.page_nbytes)

    def _run_swap_in(self, slot: Slot, req: Request, alloc) -> None:
        """Install a host-resident prefix's bytes into the pages the
        allocator reserved for it, through the jitted transport install
        (fixed [pages_per_slot] block, trash-padded — every swap mix
        hits the one compiled program). int8 pools land codes + scales
        verbatim: byte-identical to what swap-out extracted, the same
        bit-stability COW sharing relies on. One install covers the
        whole admission: a matched prefix is at most pages_per_slot - 1
        pages (the last prompt token always prefills)."""
        t0 = self._clock()
        cache, tp = self.cache, self._swap_transport
        P = cache.pages_per_slot
        rows = np.full((P,), cache.trash_page, np.int32)
        k_blk = np.zeros((cache.k.shape[0], P) + cache.k.shape[2:],
                         cache.k.dtype)
        v_blk = np.zeros_like(k_blk)
        ks_blk = vs_blk = None
        if cache.quantized:
            ks_blk = np.zeros(
                (cache.k_scale.shape[0], P) + cache.k_scale.shape[2:],
                cache.k_scale.dtype)
            vs_blk = np.zeros_like(ks_blk)
        for i, (node, page) in enumerate(alloc.swap_ins):
            data = self._host_tier.fetch(node)
            rows[i] = page
            k_blk[:, i] = data["k"]
            v_blk[:, i] = data["v"]
            if cache.quantized:
                ks_blk[:, i] = data["k_scale"]
                vs_blk[:, i] = data["v_scale"]
        # first_tok=0 rides along into the slot's last-token register —
        # dead state until prefill overwrites it (same masking argument
        # as the trash-page dead writes)
        args = (cache, self._tokens, jnp.int32(slot.index), rows,
                k_blk, v_blk, jnp.int32(0))
        if cache.quantized:
            args += (ks_blk, vs_blk)
        self._strict_audit("install", tp._install_p, args)
        with self._request_span("serving.swap_in", req, slot=slot.index,
                                pages=len(alloc.swap_ins)):
            self.cache, self._tokens = tp._install_p(*args)
        self.metrics.note_swap_in(len(alloc.swap_ins),
                                  self._clock() - t0)

    def _run_admit(self, slot: Slot, req: Request) -> None:
        key_raw = _as_raw_key(req.key)
        if key_raw is None:
            key_raw = jax.random.key_data(
                jax.random.fold_in(self._base_key, req.request_id))
        alloc = slot.alloc
        row = self._table[slot.index]
        row[:] = self.cache.trash_page
        row[:len(alloc.pages)] = alloc.pages
        if alloc.swap_ins:
            # host-resident prefix: land the swapped-out bytes in the
            # freshly reserved pages BEFORE the admit program publishes
            # the reused length (nothing reads the pages in between)
            self._run_swap_in(slot, req, alloc)
        self.metrics.note_admission(req.prompt_len, alloc.reused_len,
                                    host_pages=len(alloc.swap_ins or ()))
        self.metrics.set_page_gauges(
            self.allocator.pages_in_use, self.allocator.pages_free,
            self.allocator.pages_in_use * self.cache.page_nbytes)
        if req.trace_sampled:
            # the queue-wait span is only known in retrospect: it closes
            # the moment admission happens
            record_span("serving.queue_wait", req.submitted_at,
                        req.admitted_at, trace=req.trace_id,
                        parent=req.span_id, tenant=req.tenant)
        tail = (jnp.int32(slot.index), key_raw,
                jnp.float32(req.temperature), jnp.int32(alloc.reused_len))
        if self._spec:
            slot.draft_done = 0
            args = (self.cache, self._slot_keys, self._temps,
                    self._draft_cache.lengths) + tail
        else:
            args = (self.cache, self._slot_keys, self._temps) + tail
        self._strict_audit("admit", self._admit_p, args)
        self._ensure_cost("admit", self._admit_p, args)
        with self.cost.maybe_sample("admit", fence_in=self.cache) as sample:
            with self._request_span("serving.admit", req, slot=slot.index,
                                    reused_len=alloc.reused_len):
                if self._spec:
                    (self.cache, self._slot_keys, self._temps,
                     dlengths) = self._admit_p(*args)
                    self._draft_cache = dataclasses.replace(
                        self._draft_cache, lengths=dlengths)
                else:
                    self.cache, self._slot_keys, self._temps = \
                        self._admit_p(*args)
            sample(self.cache)
        if self.on_admit is not None:
            self.on_admit(slot, req)

    def _run_draft_chunk(self, slot: Slot, upto: int) -> None:
        """One draft-model prefill chunk over [draft_done, upto). Capped
        at `upto` (the target's prompt_done) so a catch-up over a reused
        prefix lands EXACTLY where the target sits and the two then
        advance over identical windows."""
        chunk = self.engine_config.prefill_chunk
        req = slot.request
        start = slot.draft_done
        real = min(chunk, upto - start)
        ids = np.zeros((chunk,), np.int32)
        ids[:real] = req.prompt[start:start + real]
        args = (self._draft_params, self._draft_cache,
                jnp.int32(slot.index), ids, jnp.int32(real))
        self._strict_audit("draft_prefill", self._draft_prefill_p, args)
        self._ensure_cost("draft_prefill", self._draft_prefill_p, args)
        with self.cost.maybe_sample(
                "draft_prefill", fence_in=self._draft_cache) as sample:
            with self._request_span("serving.draft_prefill", req,
                                    slot=slot.index, chunk_start=start,
                                    chunk_tokens=real), \
                    self.timer.dispatch():
                self._draft_cache = self._draft_prefill_p(*args)
            sample(self._draft_cache)
        slot.draft_done += real

    def _run_prefill_chunk(self, slot: Slot) -> None:
        chunk = self.engine_config.prefill_chunk
        req = slot.request
        if self._spec and slot.draft_done < slot.prompt_done:
            # the draft has no cached prefix to reuse: draft-only
            # catch-up chunks rebuild its prompt state up to the
            # target's reused length before the joint chunks begin.
            # NOT counted in prefill_chunks: that counter prices TARGET
            # prefill work (goodput multiplies it by the target prefill
            # program's device time, and the prefix-reuse A/B compares
            # it) — a draft-sized catch-up chunk is neither
            self._run_draft_chunk(slot, slot.prompt_done)
            return
        start = slot.prompt_done  # includes the reused prefix on a hit
        real = min(chunk, req.prompt_len - start)
        ids = np.zeros((chunk,), np.int32)
        ids[:real] = req.prompt[start:start + real]
        args = (self.params, self.cache, self._tokens, self._slot_keys,
                self._temps, jnp.int32(slot.index),
                self._table[slot.index], ids, jnp.int32(real))
        self._strict_audit("prefill", self._prefill_p, args)
        self._ensure_cost("prefill", self._prefill_p, args)
        with self.cost.maybe_sample(
                "prefill", fence_in=(self.cache, self._tokens)) as sample:
            with self._request_span("serving.prefill", req, slot=slot.index,
                                    chunk_start=start, chunk_tokens=real), \
                    self.timer.dispatch():
                self.cache, self._tokens, lp = self._prefill_p(*args)
            sample(self.cache)
        self.metrics.note_prefill_chunk()
        if self._spec:
            # joint chunk: the draft processes the same window, so both
            # prompts complete on the same engine step
            self._run_draft_chunk(slot, start + real)
        done = self.scheduler.note_prefill_chunk(slot, real)
        if req.share_prompt:
            # fork parent: every full prompt page this chunk completed
            # becomes shareable NOW — forks queued behind us map it at
            # admission instead of re-prefilling
            self.allocator.publish_prompt(slot)
        if done:
            # the chunk that completed the prompt also produced the
            # request's first token — fetch it (TTFT is measured here).
            # Index on device first: only ONE element crosses to the host,
            # not the whole [S] token vector (self-lint ATP003 class).
            tok = int(self._tokens[slot.index])
            if self.scheduler.note_token(slot, tok, logprob=float(lp)):
                self._finalize_request(req)

    def _run_decode(self, slots: list[Slot]) -> None:
        if self._spec:
            self._run_spec_decode(slots)
            return
        live = np.zeros((self.engine_config.num_slots,), bool)
        for s in slots:
            live[s.index] = True
        args = (self.params, self.cache, self._tokens, self._slot_keys,
                self._temps, live, self._table)
        self._strict_audit("decode", self._decode_p, args)
        # one decode step serves EVERY live slot, so the step span belongs
        # to no single request: span LINKS carry each sampled request's
        # trace id instead (bounded by num_slots)
        links = [s.request.trace_id for s in slots
                 if s.request is not None and s.request.trace_sampled]
        self._ensure_cost("decode", self._decode_p, args)
        with self.cost.maybe_sample(
                "decode", fence_in=(self.cache, self._tokens)) as sample:
            with span("serving.decode", links=links or None), \
                    self.timer.dispatch():
                self.cache, self._tokens, lps = self._decode_p(*args)
            sample(self.cache)
        toks = np.asarray(self._tokens)  # the per-step host read
        lps = np.asarray(lps)
        self.timer.tick(block_on=None)
        self.metrics.note_decode_step(
            "kernel" if self._use_paged_kernel else "dense")
        for s in slots:
            req = s.request
            if self.scheduler.note_token(s, int(toks[s.index]),
                                         logprob=float(lps[s.index])):
                self._finalize_request(req)

    def _run_spec_decode(self, slots: list[Slot]) -> None:
        """One speculative step for every decoding slot: draft K
        proposals per slot, verify them in ONE batched K-token target
        forward, commit the accepted prefix (plus the correction token)
        — between 1 and K tokens land per slot per step. The draft's
        cache adopts the verified lengths afterwards: by construction
        its valid rows are exactly the target's (inputs t0..d_{c-1}), so
        the two models stay position-synchronized without a catch-up."""
        K = self.engine_config.draft_k
        live = np.zeros((self.engine_config.num_slots,), bool)
        for s in slots:
            live[s.index] = True
        links = [s.request.trace_id for s in slots
                 if s.request is not None and s.request.trace_sampled]
        dargs = (self._draft_params, self._draft_cache, self._tokens,
                 self._slot_keys, self._temps)
        self._strict_audit("draft", self._draft_p, dargs)
        self._ensure_cost("draft", self._draft_p, dargs)
        with self.cost.maybe_sample(
                "draft", fence_in=self._draft_cache) as sample:
            with span("serving.draft", links=links or None), \
                    self.timer.dispatch():
                d_toks, d_logits, new_dcache = self._draft_p(*dargs)
            sample(new_dcache)
        vargs = (self.params, self.cache, self._tokens, self._slot_keys,
                 self._temps, live, self._table, d_toks, d_logits)
        self._strict_audit("verify", self._verify_p, vargs)
        self._ensure_cost("verify", self._verify_p, vargs)
        with self.cost.maybe_sample(
                "verify", fence_in=(self.cache, self._tokens)) as sample:
            with span("serving.verify", links=links or None), \
                    self.timer.dispatch():
                (self.cache, self._tokens, committed, counts, n_acc,
                 lps) = self._verify_p(*vargs)
            sample(self.cache)
        # the draft cache's valid rows now equal the target's: adopt the
        # committed lengths (rejected proposals' draft rows fall past the
        # length, masked exactly like the target's rejected rows) — but
        # for LIVE lanes only. A non-live slot holding a request is
        # mid-PREFILL, where the draft lags the target (prefix hits start
        # the target at the reused length while the draft rebuilds from
        # zero): adopting the target's length there would shift every
        # later catch-up write onto wrong rows/positions and silently
        # corrupt that request's draft state. Its true progress is the
        # host-tracked draft_done; idle lanes reset at admit, so 0 is
        # fine. The draft program advanced every lane by K regardless —
        # dead lanes' stray rows sit at/past the restored length and are
        # masked or overwritten. jnp.where yields a FRESH buffer, so the
        # pool's lengths never alias into the draft cache (the next
        # donating dispatch must not see one buffer through two args).
        restore = np.zeros((self.engine_config.num_slots,), np.int32)
        for s in self.scheduler.slots:
            if s.request is not None and not live[s.index]:
                restore[s.index] = s.draft_done
        self._draft_cache = dataclasses.replace(
            new_dcache, lengths=jnp.where(jnp.asarray(live),
                                          self.cache.lengths,
                                          jnp.asarray(restore)))
        toks = np.asarray(committed)   # [S, K] — the per-step host read
        cnts = np.asarray(counts)
        accs = np.asarray(n_acc)
        lps = np.asarray(lps)
        self.timer.tick(block_on=None)
        self.metrics.note_decode_step("speculative")
        for s in slots:
            self.metrics.note_speculation(K, int(accs[s.index]))
            req = s.request
            for j in range(int(cnts[s.index])):
                if self.scheduler.note_token(
                        s, int(toks[s.index, j]),
                        logprob=float(lps[s.index, j])):
                    # retired mid-window (budget or EOS): the remaining
                    # committed tokens are discarded — their rows sit
                    # past the slot's final length in reserved private
                    # pages and are never attended
                    self._finalize_request(req)
                    break

    # -- request tracing -----------------------------------------------------

    @staticmethod
    def _request_span(name: str, req: Request, **attrs):
        """A live span joined to the request's trace when it is sampled,
        the plain engine-wide span otherwise (engine-level spans predate
        request tracing and must keep recording for unsampled traffic)."""
        if req.trace_sampled:
            return span(name, trace=req.trace_id, parent=req.span_id,
                        **attrs)
        return span(name, **attrs)

    def _trace_terminal(self, req: Request) -> None:
        """Close the request's retrospective spans at its terminal state
        (the shared `close_request_trace` path — the pod router closes its
        requests through the same helper)."""
        end = req.finished_at
        if end is None:
            end = self._clock()
        close_request_trace(req, end)

    def _finalize_request(self, req: Request) -> None:
        """The one terminal path: close the request's trace, then fold it
        into the metrics (TTFT/per-token exemplars carry the trace id)."""
        # a terminal fork parent releases any held children (the hold
        # predicate also checks req.done — this just bounds the map)
        self._fork_parents.pop(req.request_id, None)
        self._trace_terminal(req)
        self.metrics.observe_request(req)

    # -- live introspection (the /debug endpoints read these) ----------------

    @staticmethod
    def _request_info(req: Request, now: float) -> dict:
        info = {
            "request_id": req.request_id,
            "trace_id": req.trace_id,
            "tenant": req.tenant,
            "status": req.status.value,
            "prompt_len": req.prompt_len,
            "max_new_tokens": req.max_new_tokens,
            "tokens": len(req.tokens),
            "age_s": round(now - req.submitted_at, 6),
        }
        if req.ttft_s is not None:
            info["ttft_s"] = round(req.ttft_s, 6)
        if req.slo_ttft_s is not None:
            info["slo_ttft_s"] = req.slo_ttft_s
        if req.deadline_s is not None:
            info["deadline_s"] = req.deadline_s
        if req.parent_id is not None:
            info["forked_from"] = req.parent_id
        if req.share_prompt:
            info["fork_parent"] = True
        return info

    def debug_requests(self) -> dict:
        """In-flight request state, queued and running, each entry
        carrying its trace id — the live half of 'where did the time
        go'. Read-only and JSON-safe."""
        now = self._clock()
        return {
            "queued": [self._request_info(r, now)
                       for r in self.scheduler.queue],
            "running": [self._request_info(s.request, now)
                        for s in self.scheduler.slots
                        if s.request is not None],
        }

    def debug_slots(self) -> list[dict]:
        """Slot occupancy: state, owning request/trace, prefill progress,
        and how many pool pages each slot maps."""
        out = []
        for s in self.scheduler.slots:
            entry: dict[str, Any] = {"index": s.index,
                                     "state": s.state.value}
            if s.request is not None:
                entry.update({
                    "request_id": s.request.request_id,
                    "trace_id": s.request.trace_id,
                    "tenant": s.request.tenant,
                    "prompt_done": s.prompt_done,
                    "prompt_len": s.request.prompt_len,
                    "tokens": len(s.request.tokens),
                })
                if s.alloc is not None:
                    entry["pages"] = len(s.alloc.pages)
                    entry["reused_len"] = s.alloc.reused_len
            out.append(entry)
        return out

    def debug_pages(self) -> dict:
        """Page-pool and radix-tree state: capacity, occupancy, and the
        prefix-reuse counters (host-side totals, exact)."""
        alloc = self.allocator
        return {
            "page_size": alloc.page_size,
            "num_pages": self.cache.num_pages,
            "pages_in_use": alloc.pages_in_use,
            "pages_free": alloc.pages_free,
            "prefix_cache": alloc.prefix_cache,
            "cached_pages": alloc.index.cached_pages,
            "mapped_pages": alloc.index.mapped_pages,
            "prefix_lookups": alloc.lookups,
            "prefix_hits": alloc.hits,
            "tokens_reused": alloc.tokens_reused,
            "evictions": alloc.evictions,
            "host_pages": alloc.index.host_pages,
            **({"host_tier": self._host_tier.stats()}
               if self._host_tier is not None else {}),
        }

    def debug_scheduler(self) -> dict:
        """The scheduler's policy state (per-tenant queues, DRR deficits,
        SLO EMA, shed counters)."""
        return self.scheduler.debug_state()

    def incident_dumps(self) -> dict:
        """Everything an incident bundle should freeze about this engine:
        the same snapshots the /debug endpoints serve, plus compile
        counts (a recompile storm is itself a finding). Per-section
        best-effort: the watchdog thread calls this while the engine may
        still be mutating (a slow stall is not a dead one), and one
        section's failure must not cost the others."""
        out: dict[str, Any] = {}
        for name, build in (
            ("requests", self.debug_requests),
            ("slots", self.debug_slots),
            ("pages", self.debug_pages),
            ("scheduler", self.debug_scheduler),
            ("compile_stats", self.compile_stats),
            ("cost_table", self.cost.snapshot),
        ):
            try:
                out[name] = build()
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- metrics -------------------------------------------------------------

    def _goodput(self) -> float | None:
        """Serving goodput: estimated device seconds spent producing
        tokens that were DELIVERED, over wall-clock. Decode device time
        (sampled mean x steps) counts the fraction of slot-lanes whose
        tokens reached a finished request; prefill counts the finished
        fraction of admissions (a re-prefill after a shed never
        finishes, so it drops out). Queue waits, sheds, and idle gaps
        are excluded by construction — they ARE the gap between goodput
        and 1.0. None until a device-time sample and wall window exist;
        the serving_goodput gauge tracks the latest value."""
        m = self.metrics
        if (m.started_at is None or m.stopped_at is None
                or m.stopped_at <= m.started_at):
            return None
        wall = m.stopped_at - m.started_at
        useful = 0.0
        # the decode-role program: the speculative engine's verify step
        # IS its decode (token lanes = slots x draft_k per step)
        dec = self.cost.mean_device_time(
            "verify" if self._spec else "decode")
        steps = m.decode_steps
        lanes = self.engine_config.num_slots * (
            self.engine_config.draft_k if self._spec else 1)
        if dec is not None and steps:
            useful += dec * steps * min(1.0, m.tokens_out / (steps * lanes))
        pre = self.cost.mean_device_time("prefill")
        if pre is not None and m.prefill_chunks and m.prefix_lookups:
            useful += pre * m.prefill_chunks * min(
                1.0, m.finished / m.prefix_lookups)
        if useful <= 0.0:
            return None
        g = min(1.0, useful / wall)
        m.set_goodput(g)
        return g

    def reset_metrics(self) -> None:
        """Drop accumulated samples (e.g. after a warmup pass). Compiled
        programs, slot state, and in-flight requests are untouched. The
        registry's series objects survive (zeroed in place), so the
        Prometheus endpoint and any cached metric handles stay live."""
        self.registry.reset()
        self.metrics = ServingMetrics(registry=self.registry)
        # static program costs survive a metrics reset (the compiled
        # programs didn't change) — re-set their zeroed gauges; the
        # device-time sketches restart empty with the other series
        self.cost.republish()
        self.timer = StepTimer(warmup_steps=0, registry=self.registry,
                               name="serving_step")
        # page-pool gauges reflect CURRENT state, not a window: re-sync
        # (the prefix tree and its cached pages survive a metrics reset)
        self.metrics.set_page_gauges(
            self.allocator.pages_in_use, self.allocator.pages_free,
            self.allocator.pages_in_use * self.cache.page_nbytes)
        if self._host_tier is not None:
            self.metrics.set_host_tier_gauges(self._host_tier.pages_in_use,
                                              self._host_tier.bytes_in_use)
        # decode_steps restarts from 0, so the log guard must too — a stale
        # value would swallow the first post-reset log point
        self._last_logged = 0
        # a warmup pass's compile-heavy steps would otherwise keep
        # inflating the scheduler's step-time EMA (and with it every SLO
        # floor / Retry-After estimate) long into steady state
        self.scheduler.step_time_ema = 0.0

    def metrics_summary(self) -> dict[str, float]:
        """Flat serving metrics (TTFT/per-token percentiles, occupancy,
        queue depth, tokens/sec) + the StepTimer's host-overhead meters."""
        out = self.metrics.summary()
        # pool capacity next to the in-use bytes gauge: pages a fixed HBM
        # budget holds = budget / page_nbytes, which int8 pages double
        out["pages_capacity"] = float(self.cache.num_pages)
        if self.timer._dispatch_hist.count:
            out["host_dispatch_us_mean"] = self.timer.host_dispatch_us
        # roofline attribution (ISSUE 11): measured device time per
        # program + the derived MFU / HBM-bandwidth / MXU-idle numbers
        # for decode — what the chip was DOING, not just how long. On a
        # speculative engine the decode-role program is VERIFY (the
        # batched K-token target forward), so the decode_* keys read it
        # — decode_mxu_idle_fraction stays the before/after A-vs-B
        # number ISSUE 12's acceptance quotes.
        decode_prog = "verify" if self._spec else "decode"
        for prog in (decode_prog, "prefill"):
            sheet = self.cost.roofline(prog) or {}
            name = "decode" if prog == decode_prog else prog
            if "device_time_mean_s" in sheet:
                out[f"{name}_device_time_mean_ms"] = (
                    sheet["device_time_mean_s"] * 1e3)
                out[f"{name}_device_time_p99_ms"] = (
                    sheet["device_time_p99_s"] * 1e3)
            if prog == decode_prog:
                for src, dst in (("mfu", "decode_mfu"),
                                 ("mxu_idle_fraction",
                                  "decode_mxu_idle_fraction"),
                                 ("hbm_bw_util", "decode_hbm_bw_util"),
                                 ("arith_intensity",
                                  "decode_arith_intensity")):
                    if src in sheet:
                        out[dst] = float(sheet[src])
        g = self._goodput()
        if g is not None:
            out["goodput"] = g
        out.update({f"compiles_{k}": float(v)
                    for k, v in self.compile_stats().items()})
        return out

    def close(self) -> None:
        """Stop the background observability threads (exporter, watchdog).
        Idempotent; the engine itself stays usable."""
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        if self._host_tier is not None:
            self._host_tier.close()

    def _maybe_log(self) -> None:
        if not self._tracker or not self._log_every:
            return
        steps = self.metrics.decode_steps
        # decode_steps only advances on decode, but step() also fires for
        # prefill/admission — without the last-logged guard every such step
        # re-logs the same decode step (duplicate rows; strictly-increasing
        # trackers drop them)
        if steps and steps % self._log_every == 0 and steps != self._last_logged:
            self._last_logged = steps
            self._tracker.log(self.metrics_summary(), step=steps)
