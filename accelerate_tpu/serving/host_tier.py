"""Host-DRAM overflow tier for the paged KV cache (hierarchical KV).

HBM eviction used to be destruction: a refcount-0 prefix falling out of
the page pool re-prefilled from scratch, so the effective prefix cache
was HBM-sized. On TPU hosts, DRAM is an order of magnitude larger than
HBM — this module turns it into a second cache tier:

- **Swap-out** rides the existing eviction path. When `PagedAllocator`
  evicts a refcount-0 page, it offers the victim here first
  (`HostTier.offer`). Accepting a victim dispatches the jitted
  `PageTransport` extract for that page *immediately, on the engine
  thread* — dispatch order on the device stream guarantees the gather
  reads the page's bytes before the page's next owner overwrites them —
  and hands the resulting device block to a background drain thread
  that does the device→host copy off the engine step. The radix node
  stays in the tree, flagged host-resident (cache.py `_RadixNode`), so
  the prefix still matches.

- **Swap-in** rides admission. A radix match whose tail is
  host-resident makes `PagedAllocator.allocate` reserve fresh pool
  pages for those chunks (worst-case-at-admission, so running slots
  still never hit mid-flight OOM) and report them as
  `PageAllocation.swap_ins`; the engine fetches the bytes
  (`HostTier.fetch`) and lands them through the jitted transport
  install *before* the slot's admit program runs. Hit/miss/swap mixes
  never change a program shape — compile counts stay flat (the
  transport pair compiles once each). int8 pools swap codes + scale
  blocks verbatim: no dequant/requant round-trip, so shared pages stay
  bit-stable across however many swap cycles.

- **Backpressure** never reaches decode. The drain queue is bounded;
  when it is full and the tier still has budget, the allocator *stalls
  the admission* (request stays queued, `swap_stall`) rather than
  blocking the engine thread on the queue or destroying prefixes the
  tier has room for. When the tier's byte budget itself is exhausted,
  eviction falls back to the classic destructive path.

Sizing: `capacity_pages = host_tier_bytes // cache.page_nbytes`. An
int8 pool's pages are roughly half the bytes of bf16 (codes + bf16
scales), so the same budget caches about twice the prefix tokens — and
each swap moves half the bytes over PCIe.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ..telemetry.lockwatch import maybe_tracked

__all__ = ["HostTier"]


class _HostEntry:
    """One swapped-out page, in one of two states: `device` holds the
    extracted device block until the drain thread (or a racing fetch)
    materializes it into `data` ({"k","v"[,"k_scale","v_scale"]} host
    numpy, one page each). `lock` orders drain vs. fetch — the
    swap-in-racing-eviction case where an admission wants the bytes
    before the background copy ran."""

    __slots__ = ("node", "device", "data", "lock", "cancelled")

    def __init__(self, node, device):
        self.node = node
        self.device = device
        self.data = None
        self.lock = maybe_tracked("host-tier-entry")
        self.cancelled = False


class HostTier:
    """Byte-budgeted host mirror of evicted KV pages.

    All bookkeeping (offer/fetch/discard, the entries dict, gauges)
    happens on the engine thread; the drain thread only materializes
    device blocks into host numpy. `entries` is keyed by the radix node
    object itself — node identity IS the chunk's identity for as long
    as it stays in the tree, and `PrefixIndex.drop_host` fires here the
    moment a node loses its naming path."""

    def __init__(self, engine, budget_bytes: int,
                 queue_pages: int | None = None):
        self._engine = engine
        cache = engine.cache
        self.page_nbytes = cache.page_nbytes
        self.capacity_pages = max(0, int(budget_bytes) // self.page_nbytes)
        self.queue_bound = (queue_pages if queue_pages is not None
                            else max(4, 2 * cache.pages_per_slot))
        self._entries: dict = {}
        self._queue: queue.Queue = queue.Queue(maxsize=self.queue_bound)
        self.swapped_out_pages = 0      # lifetime accepted offers
        self.swapped_in_pages = 0       # lifetime fetches
        self.rejected_pages = 0         # offers refused (budget full)
        self._closed = False
        self._drain = threading.Thread(target=self._drain_loop,
                                       name="kv-host-tier", daemon=True)
        self._drain.start()

    # -- sizing / state ------------------------------------------------------

    @property
    def pages_in_use(self) -> int:
        return len(self._entries)

    @property
    def bytes_in_use(self) -> int:
        return len(self._entries) * self.page_nbytes

    @property
    def free_pages(self) -> int:
        return self.capacity_pages - len(self._entries)

    def queue_len(self) -> int:
        return self._queue.qsize()

    def stats(self) -> dict:
        return {
            "capacity_pages": self.capacity_pages,
            "pages_in_use": self.pages_in_use,
            "bytes_in_use": self.bytes_in_use,
            "swapped_out_pages": self.swapped_out_pages,
            "swapped_in_pages": self.swapped_in_pages,
            "rejected_pages": self.rejected_pages,
            "queue_len": self.queue_len(),
        }

    # -- allocator hooks (engine thread) -------------------------------------

    def would_stall(self, need: int) -> bool:
        """True when eviction of `need` pages should wait: the tier has
        budget for at least one of them, but the bounded drain queue
        can't absorb that many offers right now. Stalling the admission
        (not the engine) lets the drain thread catch up; a budget-full
        tier never stalls — those victims evict destructively."""
        takeable = min(need, self.free_pages)
        if takeable <= 0:
            return False
        return (self.queue_bound - self._queue.qsize()) < takeable

    def offer(self, node) -> bool:
        """Accept an eviction victim into the tier, or decline (False =
        caller evicts destructively). Must run while `node.page` still
        names the bytes: the extract is dispatched here, synchronously
        in stream order, before the pool can hand the page to its next
        owner."""
        if self._closed or self.free_pages <= 0:
            self.rejected_pages += 1
            return False
        eng = self._engine
        cache = eng.cache
        row = np.full((cache.pages_per_slot,), cache.trash_page, np.int32)
        row[0] = node.page
        tp = eng._swap_transport
        eng._strict_audit("extract", tp._extract_p, (cache, row))
        entry = _HostEntry(node, tp._extract_p(cache, row))
        try:
            self._queue.put_nowait(entry)
        except queue.Full:
            # would_stall gates the common path; a race with concurrent
            # offers in one eviction burst can still land here — decline
            # rather than block the engine thread
            self.rejected_pages += 1
            return False
        self._entries[node] = entry
        self.swapped_out_pages += 1
        eng.metrics.note_swap_out(1)
        self._sync_gauges()
        return True

    def fetch(self, node) -> dict:
        """Remove and return a node's page bytes for swap-in. If the
        drain thread hasn't materialized the entry yet (swap-in racing
        its own swap-out), the copy happens here, synchronously."""
        entry = self._entries.pop(node, None)
        if entry is None:
            raise RuntimeError(
                "host tier has no entry for a host-resident node — "
                "residency bookkeeping is corrupt")
        self._materialize(entry)
        self.swapped_in_pages += 1
        self._sync_gauges()
        return entry.data

    def discard(self, node) -> None:
        """Forget a node's mirror (adoption re-homed the chunk in HBM,
        or destructive eviction severed its path). Idempotent."""
        entry = self._entries.pop(node, None)
        if entry is not None:
            entry.cancelled = True
            self._sync_gauges()

    # -- drain thread --------------------------------------------------------

    def _materialize(self, entry: _HostEntry) -> None:
        with entry.lock:
            if entry.data is not None or entry.device is None:
                return
            if entry.cancelled:
                entry.device = None
                return
            out = entry.device
            if len(out) == 4:
                k, v, ks, vs = out
                entry.data = {
                    "k": np.asarray(k)[:, 0].copy(),
                    "v": np.asarray(v)[:, 0].copy(),
                    "k_scale": np.asarray(ks)[:, 0].copy(),
                    "v_scale": np.asarray(vs)[:, 0].copy(),
                }
            else:
                k, v = out
                entry.data = {
                    "k": np.asarray(k)[:, 0].copy(),
                    "v": np.asarray(v)[:, 0].copy(),
                }
            entry.device = None

    def _drain_loop(self) -> None:
        while True:
            entry = self._queue.get()
            if entry is None:
                return
            self._materialize(entry)

    def _sync_gauges(self) -> None:
        self._engine.metrics.set_host_tier_gauges(self.pages_in_use,
                                                  self.bytes_in_use)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._drain.join(timeout=5.0)
