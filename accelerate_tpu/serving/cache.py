"""Slot-indexed KV cache for continuous batching.

`models/decode.py`'s caches carry ONE `cache_len` scalar for the whole
batch — every sequence must sit at the same depth, which is exactly what a
serving mix is not. `SlotKVCache` keeps the same layer-stacked buffer
layout ([L, S, M, H, D], S = slots) but gives every slot its own length,
so requests at different decode depths share one fixed-shape batch and one
compiled program (the pjit/TPUv4 static-shapes rule: the program is
compiled once, the *data* changes).

Correctness invariant (why retired slots never need zeroing): a write
always lands at the slot's current `length`, and the position mask
(`cached_attention_mask`) only lets queries attend cache rows `<= position
< length`. Rows at or beyond `length` — stale K/V from a retired request,
or padding from a chunked prefill — are never attended, and are overwritten
as the slot's length advances. Admission therefore just resets `length` to
zero; the O(L*M*H*D) cache wipe a naive design would pay per request is a
single scalar store.

Prefill chunks are padded to a fixed size so every chunk hits the same
compiled program; the padded tail can spill up to `chunk - 1` rows past the
slot's logical `max_len`, so the physical buffer allocates `max_len +
pad_slack` rows (`pad_slack` = the chunk size). `lengths` only ever
advances by *real* token counts, keeping the invariant above.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SlotKVCache:
    """Fixed-shape slot-indexed decode cache.

    k/v: [num_layers, num_slots, max_len + pad_slack, num_kv_heads,
    head_dim]; lengths: [num_slots] int32 — per-slot decode depth. The
    arrays are pytree children, so the whole cache threads through jit (and
    donates) like any other state; `max_len`/`pad_slack` are static.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    max_len: int
    pad_slack: int

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_slots: int,
        max_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        pad_slack: int = 0,
    ) -> "SlotKVCache":
        shape = (num_layers, num_slots, max_len + pad_slack, num_kv_heads,
                 head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((num_slots,), jnp.int32),
            max_len=max_len,
            pad_slack=pad_slack,
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def rows(self) -> int:
        """Physical rows per slot (max_len + pad_slack)."""
        return self.k.shape[2]

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


def slot_caches(cache: SlotKVCache, slot: jax.Array):
    """One slot's caches in `models/decode.py` layout: (k [L, 1, M, H, D],
    v [L, 1, M, H, D], cache_len scalar) — exactly what a family `forward`
    expects for a batch-of-one decode. `slot` may be traced (one compiled
    program covers every slot)."""
    ks = jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1)
    vs = jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1)
    return ks, vs, cache.lengths[slot]


def write_slot(cache: SlotKVCache, slot: jax.Array, new_k: jax.Array,
               new_v: jax.Array, advance: jax.Array) -> SlotKVCache:
    """Write one slot's updated [L, 1, M, H, D] buffers back and advance its
    length by `advance` REAL tokens (chunk padding is excluded by the
    caller, per the module invariant)."""
    return dataclasses.replace(
        cache,
        k=jax.lax.dynamic_update_slice_in_dim(cache.k, new_k, slot, axis=1),
        v=jax.lax.dynamic_update_slice_in_dim(cache.v, new_v, slot, axis=1),
        lengths=cache.lengths.at[slot].set(cache.lengths[slot] + advance),
    )


def reset_slot(cache: SlotKVCache, slot: jax.Array) -> SlotKVCache:
    """Admit a new request into `slot`: length back to zero. The stale K/V
    rows stay in place — the position mask hides them (see module
    docstring)."""
    return dataclasses.replace(cache,
                               lengths=cache.lengths.at[slot].set(0))


def _flatten(cache: SlotKVCache):
    return (cache.k, cache.v, cache.lengths), (cache.max_len, cache.pad_slack)


def _unflatten(aux, children):
    k, v, lengths = children
    max_len, pad_slack = aux
    return SlotKVCache(k=k, v=v, lengths=lengths, max_len=max_len,
                       pad_slack=pad_slack)


jax.tree_util.register_pytree_node(SlotKVCache, _flatten, _unflatten)
