"""KV caches for continuous batching: slot-dense and paged-with-prefix-reuse.

`models/decode.py`'s caches carry ONE `cache_len` scalar for the whole
batch — every sequence must sit at the same depth, which is exactly what a
serving mix is not. `SlotKVCache` keeps the same layer-stacked buffer
layout ([L, S, M, H, D], S = slots) but gives every slot its own length,
so requests at different decode depths share one fixed-shape batch and one
compiled program (the pjit/TPUv4 static-shapes rule: the program is
compiled once, the *data* changes).

`PagedKVCache` goes one step further: the physical buffer is a pool of
fixed-size pages ([L, pages, page_size, H, D]) and each slot owns an
ordered page table instead of a contiguous stripe. Two things fall out:

- per-request memory is sized by the request (pages allocated at
  admission), not by the engine-wide max_len;
- a page's content is position-addressed but *location-free*, so pages
  holding a shared prompt prefix can be mapped read-only into many slots
  at once. The host-side `PrefixIndex` (a radix tree over page-sized
  token chunks) remembers which pages encode which prompt prefixes;
  `PagedAllocator` matches the longest cached prefix at admission, maps
  those pages copy-on-write (refcounted — they are FULL pages and are
  never written again, so "copy" never actually happens), and releases a
  retiring request's full prompt pages back into the tree instead of
  wiping them. Prefill then runs only on the uncached suffix.

Every program stays jit-able because page tables are fixed-shape
([slots, pages_per_slot] int32, padded with a reserved trash page): the
compiled programs gather a slot's pages into the familiar contiguous
[L, 1, rows, H, D] view, run the unchanged family forward, and scatter
the updated pages back. Gather/scatter indices are traced data — the
request mix, hit/miss pattern, and eviction history never change a
program shape, so the engine's compile count stays flat.

Write-safety under sharing, the invariant the allocator maintains: only
FULL prompt pages ever enter the tree, and reuse is capped at
`(prompt_len - 1) // page_size` pages (the last prompt token always
prefills, producing the first output logits). Writes land at a slot's
current `length`, which always lies in a private page; the scatter of a
slot's whole view re-writes shared pages with their unchanged values,
which is a byte-identical no-op however many sharers race.

Correctness invariant (why retired slots never need zeroing): a write
always lands at the slot's current `length`, and the position mask
(`cached_attention_mask`) only lets queries attend cache rows `<= position
< length`. Rows at or beyond `length` — stale K/V from a retired request,
or padding from a chunked prefill — are never attended, and are overwritten
as the slot's length advances. Admission therefore just resets `length`
(to zero, or to the reused prefix length on a paged prefix hit); the
O(L*M*H*D) cache wipe a naive design would pay per request is a single
scalar store.

Prefill chunks are padded to a fixed size so every chunk hits the same
compiled program; the padded tail can spill up to `chunk - 1` rows past the
slot's logical `max_len`, so the physical buffer allocates `max_len +
pad_slack` rows (`pad_slack` = the chunk size). `lengths` only ever
advances by *real* token counts, keeping the invariant above.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SlotKVCache:
    """Fixed-shape slot-indexed decode cache.

    k/v: [num_layers, num_slots, max_len + pad_slack, num_kv_heads,
    head_dim]; lengths: [num_slots] int32 — per-slot decode depth. The
    arrays are pytree children, so the whole cache threads through jit (and
    donates) like any other state; `max_len`/`pad_slack` are static.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    max_len: int
    pad_slack: int

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_slots: int,
        max_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        pad_slack: int = 0,
    ) -> "SlotKVCache":
        shape = (num_layers, num_slots, max_len + pad_slack, num_kv_heads,
                 head_dim)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((num_slots,), jnp.int32),
            max_len=max_len,
            pad_slack=pad_slack,
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def rows(self) -> int:
        """Physical rows per slot (max_len + pad_slack)."""
        return self.k.shape[2]

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


def slot_caches(cache: SlotKVCache, slot: jax.Array):
    """One slot's caches in `models/decode.py` layout: (k [L, 1, M, H, D],
    v [L, 1, M, H, D], cache_len scalar) — exactly what a family `forward`
    expects for a batch-of-one decode. `slot` may be traced (one compiled
    program covers every slot)."""
    ks = jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1)
    vs = jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1)
    return ks, vs, cache.lengths[slot]


def write_slot(cache: SlotKVCache, slot: jax.Array, new_k: jax.Array,
               new_v: jax.Array, advance: jax.Array) -> SlotKVCache:
    """Write one slot's updated [L, 1, M, H, D] buffers back and advance its
    length by `advance` REAL tokens (chunk padding is excluded by the
    caller, per the module invariant)."""
    return dataclasses.replace(
        cache,
        k=jax.lax.dynamic_update_slice_in_dim(cache.k, new_k, slot, axis=1),
        v=jax.lax.dynamic_update_slice_in_dim(cache.v, new_v, slot, axis=1),
        lengths=cache.lengths.at[slot].set(cache.lengths[slot] + advance),
    )


def reset_slot(cache: SlotKVCache, slot: jax.Array) -> SlotKVCache:
    """Admit a new request into `slot`: length back to zero. The stale K/V
    rows stay in place — the position mask hides them (see module
    docstring)."""
    return dataclasses.replace(cache,
                               lengths=cache.lengths.at[slot].set(0))


def _flatten(cache: SlotKVCache):
    return (cache.k, cache.v, cache.lengths), (cache.max_len, cache.pad_slack)


def _unflatten(aux, children):
    k, v, lengths = children
    max_len, pad_slack = aux
    return SlotKVCache(k=k, v=v, lengths=lengths, max_len=max_len,
                       pad_slack=pad_slack)


jax.tree_util.register_pytree_node(SlotKVCache, _flatten, _unflatten)


# ---------------------------------------------------------------------------
# paged pool (device side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PagedKVCache:
    """Paged KV pool with fixed-shape per-slot page tables.

    k/v: [num_layers, num_pages + 1, page_size, num_kv_heads, head_dim] —
    the last page is the reserved TRASH page backing padded page-table
    entries (idle lanes gather it, masked rows and dead writes land in
    it, and it is never allocated). lengths: [num_slots] int32, the
    per-slot decode depth (which STARTS at the reused prefix length on a
    prefix hit). The arrays are pytree children so the cache threads
    through jit and donates; `page_size`/`pages_per_slot`/... are static.

    QUANTIZED mode (`create(kv_dtype="int8")`): k/v hold int8 codes and
    `k_scale`/`v_scale` ([L, pages+1, page_size, H] bf16, one symmetric
    absmax scale per row per head — `ops/quant.py kv_quantize_rows`)
    ride alongside as extra pytree children. Halving the bytes per page
    doubles the pages — and therefore the concurrent users — a fixed
    HBM budget holds. All writes quantize and all dense views
    dequantize (to `compute_dtype`), so the gather/scatter programs and
    the host-side page accounting are unchanged; the Pallas
    paged-attention kernel dequantizes per page in-kernel instead of
    materializing a dense copy. Per-ROW scales keep appends independent
    (a new row never re-scales a page's existing rows), which is what
    keeps shared copy-on-write pages bit-stable.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    page_size: int
    pages_per_slot: int
    max_len: int
    pad_slack: int
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None
    compute_dtype: Any = jnp.bfloat16

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_slots: int,
        max_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        page_size: int = 16,
        pad_slack: int = 0,
        num_pages: int | None = None,
        kv_dtype: Any = None,
    ) -> "PagedKVCache":
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if kv_dtype not in (None, "int8", jnp.int8):
            raise ValueError(
                f"kv_dtype must be None (store in `dtype`) or 'int8', "
                f"got {kv_dtype!r}")
        quantized = kv_dtype is not None
        # a slot's view must cover max_len rows plus the chunk-padding
        # spill (see SlotKVCache docstring) — round up to whole pages
        pages_per_slot = -(-(max_len + pad_slack) // page_size)
        if num_pages is None:
            num_pages = num_slots * pages_per_slot
        if num_pages < pages_per_slot:
            raise ValueError(
                f"num_pages({num_pages}) < pages_per_slot({pages_per_slot}):"
                " a max-size request could never be admitted")
        shape = (num_layers, num_pages + 1, page_size, num_kv_heads, head_dim)
        scale_shape = shape[:-1]
        return cls(
            k=jnp.zeros(shape, jnp.int8 if quantized else dtype),
            v=jnp.zeros(shape, jnp.int8 if quantized else dtype),
            lengths=jnp.zeros((num_slots,), jnp.int32),
            page_size=page_size,
            pages_per_slot=pages_per_slot,
            max_len=max_len,
            pad_slack=pad_slack,
            k_scale=jnp.zeros(scale_shape, jnp.bfloat16) if quantized
            else None,
            v_scale=jnp.zeros(scale_shape, jnp.bfloat16) if quantized
            else None,
            compute_dtype=dtype,
        )

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        """Allocatable pages (the +1 trash page is excluded)."""
        return self.k.shape[1] - 1

    @property
    def trash_page(self) -> int:
        """Reserved page index backing padded page-table entries."""
        return self.k.shape[1] - 1

    @property
    def num_slots(self) -> int:
        return self.lengths.shape[0]

    @property
    def rows(self) -> int:
        """Rows in one slot's gathered view (pages_per_slot * page_size)."""
        return self.pages_per_slot * self.page_size

    @property
    def page_nbytes(self) -> int:
        """HBM bytes one page costs across K and V (codes + scales in
        quantized mode) and all layers — the unit behind the
        `serving_kv_bytes_in_use` gauge and the HBM math in
        docs/serving.md: pages a budget holds = budget / page_nbytes."""
        L, _, ps, H, D = self.k.shape
        per = L * ps * H * D * self.k.dtype.itemsize
        if self.quantized:
            per += L * ps * H * self.k_scale.dtype.itemsize
        return 2 * per

    def nbytes(self) -> int:
        total = self.k.nbytes + self.v.nbytes
        if self.quantized:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return total


def _dense_pages(codes: jax.Array, scales: jax.Array | None,
                 idx: jax.Array, dtype) -> jax.Array:
    """Gather pool pages at `idx` (any int32 index shape) and materialize
    them densely: a plain gather for a bf16 pool, gather + per-row
    dequantization for an int8 pool."""
    pages = codes[:, idx]
    if scales is None:
        return pages
    from ..ops.quant import kv_dequantize_rows

    return kv_dequantize_rows(pages, scales[:, idx], dtype)


def paged_slot_view(cache: PagedKVCache, table_row: jax.Array,
                    slot: jax.Array):
    """One slot's pages gathered into `models/decode.py` layout:
    (k [L, 1, R, H, D], v [L, 1, R, H, D], length scalar), R =
    pages_per_slot * page_size, dequantized to `compute_dtype` on an
    int8 pool. `table_row` ([pages_per_slot] int32) and `slot` are
    traced — one compiled program covers every slot and every page
    mapping."""
    L, _, ps, H, D = cache.k.shape
    P = cache.pages_per_slot
    ks = _dense_pages(cache.k, cache.k_scale, table_row,
                      cache.compute_dtype).reshape(L, 1, P * ps, H, D)
    vs = _dense_pages(cache.v, cache.v_scale, table_row,
                      cache.compute_dtype).reshape(L, 1, P * ps, H, D)
    return ks, vs, cache.lengths[slot]


def paged_write_slot(cache: PagedKVCache, table_row: jax.Array,
                     slot: jax.Array, new_k: jax.Array, new_v: jax.Array,
                     advance: jax.Array, chunk: int) -> PagedKVCache:
    """Scatter the rows a prefill chunk wrote back to the pool and
    advance the slot's length by `advance` REAL tokens. The chunk only
    changes view rows [length, length + chunk), so exactly those `chunk`
    rows scatter (row -> its page via `table_row`) — per-chunk write
    traffic is O(chunk), not O(max_len), and a full-view scatter with
    traced page indices would also defeat XLA's donation aliasing (a
    pool copy per chunk). `chunk` must be a static python int. Row
    granularity (rather than the former whole-page window) is what makes
    the int8 mode safe: every written row is at or past `length`, hence
    in a PRIVATE page by the allocator's invariant — shared
    copy-on-write pages are never re-encoded, so their codes/scales stay
    bit-identical however many sharers race (an int8 round-trip is NOT
    idempotent, so rewriting a shared page with "the same values" would
    actually drift them)."""
    L, _, ps, H, D = cache.k.shape
    R = cache.rows
    length = cache.lengths[slot]
    # rows never spill past the view: length <= max_len and pad_slack
    # covers the chunk padding (module docstring)
    rows = length + jnp.arange(chunk, dtype=jnp.int32)
    pages = jnp.take(table_row, rows // ps)
    offs = rows % ps
    win_k = jnp.take(new_k.reshape(L, R, H, D), rows, axis=1)
    win_v = jnp.take(new_v.reshape(L, R, H, D), rows, axis=1)
    return _scatter_rows(cache, pages, offs, win_k, win_v,
                         cache.lengths.at[slot].set(length + advance))


def _scatter_rows(cache: PagedKVCache, pages: jax.Array, offs: jax.Array,
                  rows_k: jax.Array, rows_v: jax.Array,
                  new_lengths: jax.Array) -> PagedKVCache:
    """Scatter row payloads [L, n, H, D] at (page, offset) pairs,
    quantizing codes + per-row scales on an int8 pool. The shared tail
    of every pool write path (prefill chunks, decode appends, both
    engine attention modes)."""
    if not cache.quantized:
        return dataclasses.replace(
            cache,
            k=cache.k.at[:, pages, offs].set(rows_k.astype(cache.k.dtype)),
            v=cache.v.at[:, pages, offs].set(rows_v.astype(cache.v.dtype)),
            lengths=new_lengths,
        )
    from ..ops.quant import kv_quantize_rows

    ck, sk = kv_quantize_rows(rows_k)
    cv, sv = kv_quantize_rows(rows_v)
    return dataclasses.replace(
        cache,
        k=cache.k.at[:, pages, offs].set(ck),
        v=cache.v.at[:, pages, offs].set(cv),
        k_scale=cache.k_scale.at[:, pages, offs].set(sk),
        v_scale=cache.v_scale.at[:, pages, offs].set(sv),
        lengths=new_lengths,
    )


def paged_batch_view(cache: PagedKVCache, table: jax.Array):
    """All slots' pages gathered into the dense decode layout:
    (k [L, S, R, H, D], v [L, S, R, H, D]), dequantized to
    `compute_dtype` on an int8 pool. `table` is the full
    [S, pages_per_slot] int32 page table (traced)."""
    L, _, ps, H, D = cache.k.shape
    S = cache.num_slots
    P = cache.pages_per_slot
    ks = _dense_pages(cache.k, cache.k_scale, table,
                      cache.compute_dtype).reshape(L, S, P * ps, H, D)
    vs = _dense_pages(cache.v, cache.v_scale, table,
                      cache.compute_dtype).reshape(L, S, P * ps, H, D)
    return ks, vs


def paged_append_rows(cache: PagedKVCache, table: jax.Array,
                      row_k: jax.Array, row_v: jax.Array,
                      live: jax.Array) -> PagedKVCache:
    """Write each slot's SINGLE new row ([L, S, H, D] — the K/V of the
    token decode just produced, at view row `length`) to its page and
    advance live lanes' lengths by one. Scattering one row per slot
    keeps per-token write traffic O(slots), not O(pool) (a full-view
    scatter with dynamic page indices also defeats XLA's donation
    aliasing, so it would copy the pool every step). A live slot's
    current-length row always lies in a PRIVATE page (allocator
    invariant), so no two live lanes collide; retired lanes' tables are
    all-trash (the engine resets them at release), so their dead writes
    land in the trash page — never in a page that may have been
    reallocated. This is the write half of BOTH decode attention modes:
    the dense gather path extracts the row from the returned views
    (`paged_append_batch`), the Pallas kernel path hands the rows over
    directly."""
    _, _, ps, _, _ = cache.k.shape
    row = cache.lengths                                  # [S] view row
    page = jnp.take_along_axis(table, (row // ps)[:, None], axis=1)[:, 0]
    off = row % ps
    return _scatter_rows(cache, page, off, row_k, row_v,
                         cache.lengths + live.astype(jnp.int32))


def paged_append_batch(cache: PagedKVCache, table: jax.Array,
                       new_k: jax.Array, new_v: jax.Array,
                       live: jax.Array) -> PagedKVCache:
    """`paged_append_rows` for the dense-gather decode path, where the
    family forward returns whole updated [L, S, R, H, D] views: extract
    the one changed row per slot (view row `length`), then scatter."""
    row = cache.lengths
    idx = row[None, :, None, None, None]
    row_k = jnp.take_along_axis(new_k, idx, axis=2)[:, :, 0]   # [L, S, H, D]
    row_v = jnp.take_along_axis(new_v, idx, axis=2)[:, :, 0]
    return paged_append_rows(cache, table, row_k, row_v, live)


def paged_append_window(cache: PagedKVCache, table: jax.Array,
                        win_k: jax.Array, win_v: jax.Array,
                        counts: jax.Array, live: jax.Array) -> PagedKVCache:
    """Write each slot's next `counts[s]` rows from a fixed-width window
    ([L, S, W, H, D] — view rows [length, length + W)) and advance live
    lanes' lengths by their count. The speculative-decoding commit: the
    verify program produces W candidate rows per slot but only the
    accepted prefix is real, so rows at or past a slot's count (and every
    row of a dead lane) are routed to the trash page — the scatter stays
    fixed-shape whatever the per-slot accept counts. Every written row is
    at or past `length`, hence in a PRIVATE page (allocator invariant),
    so shared copy-on-write pages are untouched — the same write-safety
    argument as `paged_append_rows`, W rows at a time."""
    _, _, ps, _, _ = cache.k.shape
    W = win_k.shape[2]
    rows = cache.lengths[:, None] + jnp.arange(W, dtype=jnp.int32)  # [S, W]
    valid = (jnp.arange(W, dtype=jnp.int32)[None, :] < counts[:, None]) \
        & live[:, None]
    pages = jnp.take_along_axis(table, rows // ps, axis=1)
    pages = jnp.where(valid, pages, cache.trash_page)
    offs = rows % ps
    new_lengths = cache.lengths + jnp.where(live, counts, 0)
    return _scatter_rows(cache, pages, offs, win_k, win_v, new_lengths)


def paged_admit_slot(cache: PagedKVCache, slot: jax.Array,
                     reused_len: jax.Array) -> PagedKVCache:
    """Admit a request into `slot`: length starts at the reused prefix
    length (0 on a cold miss). Nothing is wiped — reused pages carry the
    prefix K/V, rows past `length` are masked until overwritten."""
    return dataclasses.replace(
        cache, lengths=cache.lengths.at[slot].set(reused_len))


def _flatten_paged(cache: PagedKVCache):
    return (cache.k, cache.v, cache.lengths, cache.k_scale, cache.v_scale), (
        cache.page_size, cache.pages_per_slot, cache.max_len,
        cache.pad_slack, cache.compute_dtype)


def _unflatten_paged(aux, children):
    k, v, lengths, k_scale, v_scale = children
    page_size, pages_per_slot, max_len, pad_slack, compute_dtype = aux
    return PagedKVCache(k=k, v=v, lengths=lengths, page_size=page_size,
                        pages_per_slot=pages_per_slot, max_len=max_len,
                        pad_slack=pad_slack, k_scale=k_scale,
                        v_scale=v_scale, compute_dtype=compute_dtype)


jax.tree_util.register_pytree_node(PagedKVCache, _flatten_paged,
                                   _unflatten_paged)


# ---------------------------------------------------------------------------
# host-side page accounting: free list + prefix radix tree + allocator
# ---------------------------------------------------------------------------


class PagePool:
    """Free list over the allocatable pages (the trash page never enters).

    Pure host bookkeeping — which physical page holds which bytes is
    entirely decided here and in `PrefixIndex`; the device only ever sees
    page indices as traced data."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, -1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Pop `n` free pages, or None (and no change) if short."""
        if n > len(self._free):
            return None
        taken = self._free[len(self._free) - n:]
        del self._free[len(self._free) - n:]
        return taken[::-1]

    def release(self, pages) -> None:
        self._free.extend(pages)


class _RadixNode:
    """One cached page: `key` is the page's token chunk (bytes of
    page_size int32 tokens), `page` its physical index. `refcount` counts
    live slots currently mapping the page; 0 means cached-but-unmapped
    (evictable once it is a leaf).

    `residency` is the hierarchical-KV state: "hbm" means `page` is a
    live pool page holding the chunk's K/V; "host" means the chunk's
    bytes were swapped out to the host tier (serving/host_tier.py) —
    `page` is -1, the node stays in the tree so the prefix still
    matches, and a later admission swaps the bytes back into a freshly
    reserved pool page. A host-resident node is always refcount-0 (a
    mapped node's page is pinned in HBM) and all of its children are
    host-resident too: eviction drains leaf-first, so residency along
    any root path is an HBM prefix followed by a host suffix."""

    __slots__ = ("key", "page", "children", "refcount", "last_used",
                 "parent", "residency")

    def __init__(self, key: bytes, page: int, parent: "_RadixNode | None"):
        self.key = key
        self.page = page
        self.children: dict[bytes, _RadixNode] = {}
        self.refcount = 0
        self.last_used = 0
        self.parent = parent
        self.residency = "hbm"


class PrefixIndex:
    """Radix tree over page-sized token chunks -> cached KV pages.

    Each edge consumes exactly `page_size` token IDs (reuse is
    page-granular: a prefix is reusable only in whole pages, which is
    also what makes the cached pages immutable — see the module
    docstring), so the tree IS the map from prompt prefixes to page
    lists. Nodes are LRU-stamped on every match/insert; eviction frees
    refcount-0 LEAVES oldest-first, which keeps every cached path
    contiguous from the root (an interior node is unevictable while any
    descendant survives, and a mapped page — refcount > 0 — is never
    evicted)."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _RadixNode(b"", -1, None)
        self._tick = 0
        self.cached_pages = 0   # HBM-resident nodes (pool pages in the tree)
        self.mapped_pages = 0   # nodes with refcount > 0 (always HBM)
        self.host_pages = 0     # host-resident nodes (bytes in the host tier)
        # drop_host(node): the host tier forgets `node`'s swapped-out
        # bytes. Fired when a host-resident chunk is re-homed in HBM by a
        # fresh insert (adoption) or its naming path is destructively
        # evicted. None when no host tier is attached.
        self.drop_host: Callable[[Any], None] | None = None

    def _touch(self, node: _RadixNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    def _chunk(self, prompt: np.ndarray, i: int) -> bytes:
        ps = self.page_size
        return np.ascontiguousarray(
            prompt[i * ps:(i + 1) * ps], dtype=np.int32).tobytes()

    def match(self, prompt: np.ndarray) -> list[_RadixNode]:
        """Longest cached prefix of `prompt`, as the node path from the
        root, capped at (prompt_len - 1) // page_size pages so at least
        one prompt token always prefills (the first output token's
        logits have to come from somewhere)."""
        limit = (int(prompt.shape[0]) - 1) // self.page_size
        node, path = self.root, []
        for i in range(limit):
            child = node.children.get(self._chunk(prompt, i))
            if child is None:
                break
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def acquire(self, nodes: list[_RadixNode]) -> None:
        for n in nodes:
            n.refcount += 1
            if n.refcount == 1:
                self.mapped_pages += 1

    def release(self, nodes: list[_RadixNode]) -> None:
        for n in nodes:
            n.refcount -= 1
            if n.refcount == 0:
                self.mapped_pages -= 1

    def insert(self, prompt: np.ndarray, pages: list[int],
               upto_pages: int) -> list[int]:
        """Cache prompt pages [0, upto_pages): walk/create the node path,
        adopting `pages[i]` for chunks not yet cached. Returns the pages
        NOT adopted (an equal chunk was cached concurrently by another
        request — the caller frees the duplicates)."""
        node, spare = self.root, []
        for i in range(upto_pages):
            key = self._chunk(prompt, i)
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(key, pages[i], node)
                node.children[key] = child
                self.cached_pages += 1
            elif child.residency == "host":
                # the chunk was swapped out while this request prefilled
                # its own copy — adopt the fresh HBM page (value-identical
                # bytes) and let the host tier drop the stale mirror
                self._adopt_host(child, pages[i])
            elif child.page != pages[i]:
                spare.append(pages[i])
            self._touch(child)
            node = child
        return spare

    def _adopt_host(self, node: _RadixNode, page: int) -> None:
        """Re-home a host-resident node in HBM at `page` (whose bytes
        must already hold the chunk's K/V) and drop the host mirror."""
        node.page = page
        node.residency = "hbm"
        self.host_pages -= 1
        self.cached_pages += 1
        if self.drop_host is not None:
            self.drop_host(node)

    def extend_path(self, prompt: np.ndarray, pages: list[int],
                    start: int, upto: int) -> list[_RadixNode]:
        """Walk/create nodes for chunks [start, upto) of `prompt`,
        adopting `pages[i]` for chunks not yet cached — the mid-flight
        half of `insert`, used by `PagedAllocator.publish_prompt` to
        share a RUNNING request's already-prefilled prompt pages (COW
        request forking). Stops at the first chunk already cached under
        a DIFFERENT page: past that point the caller's pages can't back
        the tree path, and the pages[:len(nodes)]-are-node-pages
        invariant of `PageAllocation` must hold for the extended node
        list. The first `start` chunks must already be the caller's
        mapped (refcount > 0, hence unevictable) path. Returned nodes
        are refcount-0 until the caller acquires them."""
        node = self.root
        for i in range(start):
            node = node.children[self._chunk(prompt, i)]
        out: list[_RadixNode] = []
        for i in range(start, upto):
            key = self._chunk(prompt, i)
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(key, pages[i], node)
                node.children[key] = child
                self.cached_pages += 1
            elif child.residency == "host":
                # same adoption as `insert`: the publisher's freshly
                # prefilled page re-homes the swapped-out chunk in HBM
                self._adopt_host(child, pages[i])
            elif child.page != pages[i]:
                break
            self._touch(child)
            out.append(child)
            node = child
        return out

    def evict_lru(self, n: int,
                  swap_out: "Callable[[Any], bool] | None" = None
                  ) -> list[int]:
        """Free exactly `n` pages, draining least-recently-used
        refcount-0 effective leaves (an effective leaf is an HBM node
        with no HBM descendant — host-resident children don't pin their
        parent, or a host tier would freeze eviction; draining one can
        turn its parent into the next candidate). Mapped pages
        (refcount > 0) are never touched. ALL-OR-NOTHING: if fewer than
        `n` pages are evictable the tree is left intact and [] returned
        — a failed admission must not cost the cache its reusable
        prefixes, and (key for a queue head that stays blocked for many
        engine steps) that case bails in O(1).

        `swap_out(node)` (the host tier's offer, while `node.page` still
        names the bytes) decides each victim's fate: True keeps the node
        in the tree as host-resident (page freed, bytes mirrored to host
        DRAM); False/None is the classic destructive eviction — the node
        detaches, and any host-resident subtree hanging under it loses
        its naming path, so those mirrors are dropped via `drop_host`.
        Either way exactly one HBM page per victim is freed.

        Why `cached - mapped` IS the evictable total: acquire/release
        always ref whole root-paths (`match` returns contiguous paths
        from the root), so refcounts are downward-closed — a refcount-0
        node can never have a mapped descendant, and every refcount-0
        subtree drains leaf-first (host-resident nodes are refcount-0 by
        construction and hold no HBM page, so they count in neither
        term). The sufficient case pays one DFS plus a min-heap of
        candidate leaves: O(tree + n log tree), once per actual eviction
        burst, never per blocked step."""
        if n <= 0 or self.cached_pages - self.mapped_pages < n:
            return []
        heap = []
        stack = [c for c in self.root.children.values()
                 if c.residency == "hbm"]
        while stack:
            node = stack.pop()
            hbm_children = [c for c in node.children.values()
                            if c.residency == "hbm"]
            if hbm_children:
                stack.extend(hbm_children)
            elif node.refcount == 0:
                heap.append((node.last_used, node.page, node))
        heapq.heapify(heap)
        freed: list[int] = []
        while len(freed) < n:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            freed.append(victim.page)
            self.cached_pages -= 1
            if swap_out is not None and swap_out(victim):
                victim.page = -1
                victim.residency = "host"
                self.host_pages += 1
            else:
                del parent.children[victim.key]
                victim.parent = None
                # every descendant of an effective leaf is host-resident;
                # their mirrors die with the path that named them
                drop_stack = list(victim.children.values())
                while drop_stack:
                    orphan = drop_stack.pop()
                    drop_stack.extend(orphan.children.values())
                    self.host_pages -= 1
                    if self.drop_host is not None:
                        self.drop_host(orphan)
            if parent is not self.root and parent.refcount == 0 \
                    and parent.residency == "hbm" \
                    and not any(c.residency == "hbm"
                                for c in parent.children.values()):
                heapq.heappush(heap, (parent.last_used, parent.page, parent))
        return freed

    def residency_probe(self, prompt: np.ndarray) -> tuple[int, int]:
        """(hbm_pages, host_pages) along the longest cached prefix of
        `prompt`, WITHOUT touching LRU stamps — the pod router's
        placement probe (scoring a worker must not make its cache look
        hot)."""
        limit = (int(prompt.shape[0]) - 1) // self.page_size
        node, hbm, host = self.root, 0, 0
        for i in range(limit):
            child = node.children.get(self._chunk(prompt, i))
            if child is None:
                break
            if child.residency == "hbm":
                hbm += 1
            else:
                host += 1
            node = child
        return hbm, host


@dataclasses.dataclass
class PageAllocation:
    """One admitted request's page mapping: `pages` is the ordered table
    row prefix (cached prefix pages first, then private pages); `nodes`
    are the mapped radix nodes backing pages[:len(nodes)].

    `swap_ins` lists (node, page) pairs whose chunks matched
    host-resident: the allocator already reserved `page` and re-homed
    the node, but the BYTES are still in the host tier — the engine must
    install them (jitted PageTransport install) before the slot's admit
    program runs, or the reused prefix serves garbage."""

    reused_len: int
    nodes: list
    pages: list[int]
    swap_ins: list | None = None


class PagedAllocator:
    """Admission-time page allocation with prefix reuse.

    The scheduler calls `allocate()` before admitting a queued request
    (None = not enough pages yet, the request stays queued — transient
    pressure, relieved as running slots retire) and `release()` when a
    slot retires or is cancelled. Worst-case pages are reserved at
    admission, so a running request can never hit pool pressure
    mid-flight and never needs preemption."""

    def __init__(
        self,
        page_size: int,
        num_pages: int,
        pad_slack: int = 0,
        prefix_cache: bool = True,
        on_evict: Callable[[int], None] | None = None,
        on_unmap: Callable[[int], None] | None = None,
    ):
        self.page_size = page_size
        self.pad_slack = pad_slack
        self.prefix_cache = prefix_cache
        self.pool = PagePool(num_pages)
        self.index = PrefixIndex(page_size)
        self.on_evict = on_evict
        self.on_unmap = on_unmap
        # admission-hold hook: hold_admission(request) -> True keeps the
        # request queued even when pages ARE available. The engine uses
        # it for COW forks: a fork child admitted before its parent's
        # prompt pages are published would cold-prefill the very prompt
        # it was forked to share — waiting the few steps until the
        # parent's prefill publishes them is what makes an n-way fan-out
        # cost ONE prefill. Same no-skip-ahead semantics as a pages-tight
        # head: the queue waits behind it.
        self.hold_admission: Callable[[Any], bool] | None = None
        # host-tier hooks (engine-wired when EngineConfig.host_tier_bytes
        # > 0, else None and eviction stays destructive):
        #   swap_out(node) -> bool — offer an eviction victim to the host
        #     tier while node.page still names its bytes; True = accepted
        #     (node goes host-resident), False = tier full, evict
        #     destructively.
        #   swap_stall(need) -> bool — True when the tier WOULD accept
        #     victims but its bounded swap-out queue can't absorb `need`
        #     more pages right now: the admission stalls (request stays
        #     queued, decode never blocks) instead of either blocking on
        #     the queue or destroying prefixes the tier has room for.
        self.swap_out: Callable[[Any], bool] | None = None
        self.swap_stall: Callable[[int], bool] | None = None
        # running totals for host-side (model-free) observability and
        # tests. The engine's registry counters are booked separately:
        # evictions reach it through on_evict, admission outcomes through
        # Engine._run_admit reading the same PageAllocation.
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0
        self.evictions = 0

    @property
    def pages_free(self) -> int:
        return self.pool.free_count

    @property
    def pages_in_use(self) -> int:
        """Allocated to live slots OR cached in the prefix tree."""
        return self.pool.used_count

    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case pages for one request: every prompt+generated row
        plus the chunk-padding spill, in whole pages."""
        rows = prompt_len + max_new_tokens + self.pad_slack
        return -(-rows // self.page_size)

    def allocate(self, request) -> PageAllocation | None:
        """Match the longest cached prefix and reserve the remaining
        private pages, evicting LRU refcount-0 pages under pressure.
        None = insufficient pages even with eviction (keep queued) — and
        in that case NOTHING was evicted (evict_lru is all-or-nothing),
        so a too-big queue head can't strip the cache while it waits."""
        if self.hold_admission is not None and self.hold_admission(request):
            return None
        path = (self.index.match(request.prompt)
                if self.prefix_cache else [])
        # residency along a matched path is an HBM prefix then a host
        # suffix (leaf-first eviction — see _RadixNode); the host suffix
        # needs fresh pool pages to swap back into, reserved here with
        # the same worst-case discipline as private pages
        n_hbm = 0
        while n_hbm < len(path) and path[n_hbm].residency == "hbm":
            n_hbm += 1
        hbm_nodes, host_nodes = path[:n_hbm], path[n_hbm:]
        n_total = self.pages_needed(request.prompt_len,
                                    request.max_new_tokens)
        n_extra = n_total - n_hbm   # swap-in pages + private pages
        # acquire BEFORE evicting: matched nodes are refcount-0 until
        # mapped, and eviction must never free a page we are about to
        # use. Host nodes can't be acquired yet (mapped_pages counts HBM
        # pages) but are eviction-proof anyway: eviction only drops a
        # host subtree under a destructively evicted HBM ancestor, and
        # every HBM ancestor of `host_nodes` is in `hbm_nodes` — pinned.
        self.index.acquire(hbm_nodes)
        try:
            extra = self.pool.alloc(n_extra)
            if extra is None:
                need = n_extra - self.pool.free_count
                if self.swap_stall is not None and self.swap_stall(need):
                    self.index.release(hbm_nodes)
                    return None
                freed = self.index.evict_lru(need, swap_out=self.swap_out)
                if freed:
                    self.evictions += len(freed)
                    self.pool.release(freed)
                    if self.on_evict is not None:
                        self.on_evict(len(freed))
                extra = self.pool.alloc(n_extra)
            if extra is None:
                self.index.release(hbm_nodes)
                return None
            # re-home the host suffix: each node takes a reserved page
            # NOW (bookkeeping only — the caller installs the bytes
            # before the slot's first device program reads them)
            swap_ins = []
            for node, page in zip(host_nodes, extra):
                node.page = page
                node.residency = "hbm"
                self.index.host_pages -= 1
                self.index.cached_pages += 1
                swap_ins.append((node, page))
            self.index.acquire(host_nodes)
        except BaseException:
            # on_evict / swap_stall are caller-supplied callbacks: if
            # one raises mid-allocate the matched nodes' refcounts must
            # not leak (they would pin their whole root paths
            # unevictable forever — the ATP201 self-lint finding this
            # handler exists for)
            self.index.release(hbm_nodes)
            raise
        private = extra[len(host_nodes):]
        self.lookups += 1
        if path:
            self.hits += 1
            self.tokens_reused += len(path) * self.page_size
        # ownership of the acquired refcounts transfers to the returned
        # allocation here (hbm prefix + re-homed host suffix == path)
        return PageAllocation(
            reused_len=len(path) * self.page_size,
            nodes=hbm_nodes + host_nodes,
            pages=[n.page for n in hbm_nodes + host_nodes] + private,
            swap_ins=swap_ins or None,
        )

    def rollback(self, alloc: PageAllocation) -> None:
        """Undo an `allocate()` whose slot attachment never happened (the
        pod router's adopt race): shared nodes drop their refcount,
        private pages return to the free list, nothing is cached. The
        inverse of allocate lives HERE so the [node pages | private]
        layout of PageAllocation.pages stays a single-module invariant.
        Pending swap-ins revert to host residency — their bytes were
        never installed, so the reserved pages return to the pool and the
        host tier keeps the mirror."""
        self.index.release(alloc.nodes)
        self.pool.release(alloc.pages[len(alloc.nodes):])
        for node, page in (alloc.swap_ins or ()):
            node.page = -1
            node.residency = "host"
            self.index.host_pages += 1
            self.index.cached_pages -= 1
            self.pool.release([page])
        self.lookups -= 1
        if alloc.nodes:
            self.hits -= 1
            self.tokens_reused -= alloc.reused_len

    def publish_prompt(self, slot) -> int:
        """Insert a RUNNING slot's already-prefilled FULL prompt pages
        into the prefix tree NOW, instead of waiting for retirement —
        the mechanism behind engine-level COW request forking: a fork of
        this request admitted later maps these pages instead of
        re-prefilling the prompt. Only pages every row of which holds
        final real-token K/V are published (prefill writes always land
        at or past the slot's current length, so a full page below
        `prompt_done` is immutable from here on — the same invariant
        retirement-inserted pages rely on). The published nodes are
        acquired into the slot's own allocation, so they are mapped
        (unevictable) for as long as the slot runs, and `release()` later
        drops them exactly like an admission-time prefix hit. Returns
        the number of prompt pages now shared. Idempotent; no-op when
        the prefix cache is off."""
        if not self.prefix_cache:
            return 0
        alloc, req = slot.alloc, slot.request
        if alloc is None:
            return 0
        full = min(slot.prompt_done, req.prompt_len) // self.page_size
        n_cached = len(alloc.nodes)
        if full <= n_cached:
            return n_cached
        new_nodes = self.index.extend_path(req.prompt, alloc.pages,
                                           n_cached, full)
        self.index.acquire(new_nodes)
        alloc.nodes.extend(new_nodes)
        return len(alloc.nodes)

    def release(self, slot, finished: bool) -> None:
        """Return a retiring slot's pages: shared nodes drop a refcount
        (other sharers keep decoding untouched); on a normal finish the
        FULL prompt pages are inserted into the tree (content intact —
        this is the 'release to the tree, not wipe' half of reuse); the
        rest — generation pages, the partial last prompt page, and pages
        whose chunks a concurrent request cached first — go back to the
        free list. `finished=False` (cancel) caches nothing: a
        mid-prefill page may hold garbage.

        The insertable range is additionally capped at the slot's
        PREFILLED prompt, not the whole prompt: `finish_early` can
        retire a slot whose prefill is still mid-flight (a server-side
        stop decision), and inserting pages past `prompt_done` would
        cache never-written garbage KV that a later prefix hit serves
        as real prompt state — silent corruption, surfaced while
        building the ATP2xx/sanitizer audit and pinned model-free in
        test_paged_cache."""
        alloc, req = slot.alloc, slot.request
        self.index.release(alloc.nodes)
        n_cached = len(alloc.nodes)
        full = min(req.prompt_len, slot.prompt_done) // self.page_size \
            if (finished and self.prefix_cache) else n_cached
        spare = (self.index.insert(req.prompt, alloc.pages, full)
                 if full > n_cached else [])
        self.pool.release(spare + alloc.pages[full:])
        if self.on_unmap is not None:
            self.on_unmap(slot.index)
