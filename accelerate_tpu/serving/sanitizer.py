"""Serving-state sanitizer: the runtime half of the ATP2xx lifecycle
audit (`analysis/lifecycle.py` is the static half).

Static analysis proves per-function acquire/release discipline; what it
CANNOT see is whether the cross-structure books still agree at runtime —
the page free list vs the radix tree vs the slot allocations vs the
device page tables vs the scheduler's tenant queues. The sanitizer
validates exactly those joins after every engine step:

- **page conservation**: every allocatable page is in exactly one place
  — the free list, the radix tree, or some slot's private allocation;
  the trash page is in none of them; nothing is double-owned; with a
  host tier on, host-resident radix nodes hold no HBM page at all but
  must each have a live mirror entry in the tier (and vice versa), host
  residency is downward-closed (a host node's children are all host),
  the tier stays inside its byte budget, and its drain queue inside its
  bound;
- **refcount correctness**: each radix node's refcount equals the number
  of live slot allocations mapping it, refcounts are downward-closed
  along root paths (a refcount-0 node never has a mapped descendant —
  the invariant `evict_lru`'s O(1) bail relies on), and the
  `cached_pages`/`mapped_pages` running counters match the tree;
- **table discipline**: a slot's device page-table row is exactly its
  allocation followed by trash padding; idle lanes are all-trash (a
  stale row is how a retired lane's masked writes corrupt a reallocated
  page);
- **length bounds**: a live slot's decode length stays within the rows
  its allocation reserved (and a speculative engine's draft lengths
  match the host-tracked draft progress for prefilling lanes);
- **scheduler books**: queued requests are QUEUED, running slots hold
  RUNNING requests, per-tenant queues/deficits/tier rings stay aligned
  with the tenant table.

All host-side: no program changes, no extra compiles (the acceptance
guard pins compile counts flat with the sanitizer on). Enabled via
`EngineConfig(sanitize=True)` — or the `ACCELERATE_TPU_SANITIZE` env var,
which the test suite sets so every tier-1 engine runs sanitized.
Violations raise :class:`SanitizerViolation` naming the broken invariant
with enough detail to act on, and the engine attaches the incident-bundle
machinery (`EngineConfig(incident_dir=...)`) before re-raising.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from .scheduler import RequestStatus, SlotState

__all__ = ["SanitizerViolation", "resolve_sanitize", "check_engine",
           "check_router", "check_distributed_router"]

SANITIZE_ENV = "ACCELERATE_TPU_SANITIZE"


class SanitizerViolation(RuntimeError):
    """One broken cross-structure invariant. `check` is the stable
    invariant name (page-conservation, refcount, table, lengths,
    scheduler-books, router-books); `details` is a JSON-safe dict that
    lands in the incident bundle."""

    def __init__(self, check: str, message: str,
                 details: dict | None = None):
        self.check = check
        self.details = details or {}
        detail_txt = ""
        if details:
            rendered = ", ".join(f"{k}={v!r}" for k, v in details.items())
            detail_txt = f" ({rendered})"
        super().__init__(f"serving-state sanitizer: [{check}] "
                         f"{message}{detail_txt}")


def resolve_sanitize(setting: Any) -> bool:
    """EngineConfig.sanitize -> bool. None defers to the
    ACCELERATE_TPU_SANITIZE env var (truthy = on), unset = off."""
    if setting is not None:
        return bool(setting)
    raw = os.environ.get(SANITIZE_ENV, "").strip().lower()
    return raw in ("1", "true", "yes", "on")


def _fail(check: str, message: str, **details) -> None:
    raise SanitizerViolation(check, message, details)


def _walk_tree(index) -> list:
    """[(node, parent)] over the radix tree, root excluded."""
    out = []
    stack = [(child, index.root)
             for child in index.root.children.values()]
    while stack:
        node, parent = stack.pop()
        out.append((node, parent))
        stack.extend((c, node) for c in node.children.values())
    return out


def check_engine(engine) -> None:
    """Validate one Engine's cross-structure invariants; raises
    :class:`SanitizerViolation` on the first broken one."""
    alloc = engine.allocator
    pool, index = alloc.pool, alloc.index
    num_pages = pool.num_pages
    trash = engine.cache.trash_page
    sched = engine.scheduler

    # -- page conservation ---------------------------------------------------
    free = list(pool._free)
    free_set = set(free)
    if len(free_set) != len(free):
        _fail("page-conservation", "free list holds duplicate pages",
              duplicates=sorted(p for p in free_set
                                if free.count(p) > 1))
    bad = [p for p in free_set if not (0 <= p < num_pages)]
    if bad:
        _fail("page-conservation",
              "free list holds out-of-range pages (the trash page must "
              "never be allocatable)", pages=sorted(bad), trash=trash)
    tree_nodes = _walk_tree(index)
    tree_pages: dict = {}
    host_nodes = []
    for node, parent in tree_nodes:
        if getattr(node, "residency", "hbm") == "host":
            host_nodes.append(node)
            if node.page != -1:
                _fail("page-conservation",
                      "a host-resident radix node still names an HBM page",
                      page=node.page)
            if node.refcount != 0:
                _fail("page-conservation",
                      "a host-resident radix node is mapped by a slot "
                      "(swap-in must re-home before acquire)",
                      refcount=node.refcount)
            if any(getattr(c, "residency", "hbm") == "hbm"
                   for c in node.children.values()):
                _fail("page-conservation",
                      "a host-resident radix node has an HBM child "
                      "(residency must be a suffix property — eviction "
                      "drains leaf-first)")
            continue
        if parent is not index.root and \
                getattr(parent, "residency", "hbm") == "host":
            _fail("page-conservation",
                  "an HBM radix node hangs under a host-resident parent "
                  "(residency must be a suffix property)",
                  page=node.page)
        if node.page in tree_pages:
            _fail("page-conservation",
                  "one physical page backs two radix nodes",
                  page=node.page)
        if not (0 <= node.page < num_pages):
            _fail("page-conservation", "radix node holds an out-of-range "
                  "page", page=node.page)
        tree_pages[node.page] = node
    host_tier = getattr(engine, "_host_tier", None)
    if host_nodes and host_tier is None:
        _fail("page-conservation",
              "host-resident radix nodes exist but the engine has no "
              "host tier", host_nodes=len(host_nodes))
    if host_tier is not None:
        if index.host_pages != len(host_nodes):
            _fail("page-conservation",
                  "host_pages counter disagrees with the tree",
                  counter=index.host_pages, tree=len(host_nodes))
        entry_nodes = set(map(id, host_tier._entries))
        missing = [n for n in host_nodes if id(n) not in entry_nodes]
        if missing:
            _fail("page-conservation",
                  "host-resident radix nodes lack a host-tier mirror "
                  "entry (their bytes are gone — a hit would install "
                  "garbage)", nodes=len(missing))
        if len(host_tier._entries) != len(host_nodes):
            _fail("page-conservation",
                  "host-tier mirror entries outlive their radix nodes "
                  "(the tier's budget leaks)",
                  entries=len(host_tier._entries),
                  host_nodes=len(host_nodes))
        if host_tier.pages_in_use > host_tier.capacity_pages:
            _fail("page-conservation",
                  "host tier exceeded its byte budget",
                  pages_in_use=host_tier.pages_in_use,
                  capacity_pages=host_tier.capacity_pages)
        if host_tier.queue_len() > host_tier.queue_bound:
            _fail("page-conservation",
                  "host-tier drain queue exceeded its bound "
                  "(backpressure is not reaching admission)",
                  queue_len=host_tier.queue_len(),
                  bound=host_tier.queue_bound)
    slot_allocs = [(s, s.alloc) for s in sched.slots if s.alloc is not None]
    private_owner: dict = {}
    for slot, a in slot_allocs:
        node_pages = [n.page for n in a.nodes]
        if a.pages[:len(a.nodes)] != node_pages:
            _fail("page-conservation",
                  "a slot allocation's leading pages disagree with its "
                  "mapped radix nodes", slot=slot.index,
                  pages=a.pages[:len(a.nodes)], node_pages=node_pages)
        for p in a.pages[len(a.nodes):]:
            if p in private_owner:
                _fail("page-conservation",
                      "one private page is owned by two slots (COW "
                      "isolation broken)", page=p,
                      slots=[private_owner[p], slot.index])
            if p in tree_pages:
                _fail("page-conservation",
                      "a slot's PRIVATE page is simultaneously cached in "
                      "the radix tree", page=p, slot=slot.index)
            if p in free_set:
                _fail("page-conservation",
                      "a slot's private page is also on the free list",
                      page=p, slot=slot.index)
            private_owner[p] = slot.index
    overlap = free_set & set(tree_pages)
    if overlap:
        _fail("page-conservation",
              "pages are both free and cached in the radix tree",
              pages=sorted(overlap))
    accounted = len(free_set) + len(tree_pages) + len(private_owner)
    if accounted != num_pages:
        _fail("page-conservation",
              "pages lost or double-counted: free + cached + private != "
              "pool size", free=len(free_set), cached=len(tree_pages),
              private=len(private_owner), pool=num_pages)

    # -- refcounts -----------------------------------------------------------
    refcounts: dict = {}
    for slot, a in slot_allocs:
        for n in a.nodes:
            refcounts[id(n)] = refcounts.get(id(n), 0) + 1
    mapped = 0
    for node, parent in tree_nodes:
        want = refcounts.get(id(node), 0)
        if node.refcount != want:
            _fail("refcount",
                  "a radix node's refcount disagrees with the live slot "
                  "allocations mapping it", page=node.page,
                  refcount=node.refcount, mapped_by_slots=want)
        if node.refcount > 0:
            mapped += 1
            if parent is not index.root and parent.refcount == 0:
                _fail("refcount",
                      "refcounts are not downward-closed: a mapped node "
                      "hangs under a refcount-0 parent (evict_lru's "
                      "accounting would evict a mapped page)",
                      page=node.page, parent_page=parent.page)
    if index.cached_pages != len(tree_pages):
        _fail("refcount", "cached_pages counter disagrees with the tree",
              counter=index.cached_pages, tree=len(tree_pages))
    if index.mapped_pages != mapped:
        _fail("refcount", "mapped_pages counter disagrees with the tree",
              counter=index.mapped_pages, tree=mapped)

    # -- device page tables --------------------------------------------------
    table = engine._table
    for slot in sched.slots:
        row = table[slot.index]
        if slot.alloc is not None:
            a = slot.alloc
            if list(row[:len(a.pages)]) != list(a.pages):
                _fail("table",
                      "a live slot's device table row disagrees with its "
                      "allocation", slot=slot.index,
                      row=[int(x) for x in row[:len(a.pages)]],
                      alloc=list(a.pages))
            tail = row[len(a.pages):]
        else:
            tail = row
        if not np.all(tail == trash):
            _fail("table",
                  "rows past a slot's allocation (or an idle slot's whole "
                  "row) must be trash-padded — a stale entry lets masked "
                  "ride-along writes land in someone else's page",
                  slot=slot.index,
                  row=[int(x) for x in np.asarray(row)])

    # -- length bounds -------------------------------------------------------
    lengths = np.asarray(engine.cache.lengths)
    ps = engine.cache.page_size
    for slot in sched.slots:
        if slot.alloc is None or slot.request is None:
            continue
        cap = len(slot.alloc.pages) * ps
        length = int(lengths[slot.index])
        if not (0 <= length <= cap):
            _fail("lengths",
                  "a live slot's decode length escaped the rows its "
                  "allocation reserved", slot=slot.index, length=length,
                  reserved_rows=cap)
    if getattr(engine, "_spec", False):
        dlengths = np.asarray(engine._draft_cache.lengths)
        for slot in sched.slots:
            if slot.request is None:
                continue
            if slot.state is SlotState.PREFILL:
                if int(dlengths[slot.index]) != slot.draft_done:
                    _fail("lengths",
                          "a prefilling slot's draft cache length "
                          "disagrees with its host-tracked draft progress "
                          "(the PR 12 catch-up corruption class)",
                          slot=slot.index,
                          draft_len=int(dlengths[slot.index]),
                          draft_done=slot.draft_done)

    # -- scheduler books -----------------------------------------------------
    depth = 0
    for name, q in sched._queues.items():
        depth += len(q)
        for r in q:
            if r.status is not RequestStatus.QUEUED:
                _fail("scheduler-books",
                      "a queued request is not in QUEUED state",
                      tenant=name, request_id=r.request_id,
                      status=r.status.value)
            if r.tenant != name:
                _fail("scheduler-books",
                      "a request sits in another tenant's queue",
                      queue=name, tenant=r.tenant,
                      request_id=r.request_id)
    if depth != sched.queue_depth:
        _fail("scheduler-books", "queue_depth disagrees with the queues",
              computed=depth, reported=sched.queue_depth)
    for slot in sched.slots:
        if slot.request is not None:
            if slot.state is SlotState.IDLE:
                _fail("scheduler-books",
                      "an IDLE slot still holds a request",
                      slot=slot.index,
                      request_id=slot.request.request_id)
            if slot.request.status is not RequestStatus.RUNNING:
                _fail("scheduler-books",
                      "a slot's request is not RUNNING",
                      slot=slot.index,
                      request_id=slot.request.request_id,
                      status=slot.request.status.value)
            if slot.prompt_done > slot.request.prompt_len:
                _fail("scheduler-books",
                      "prefill progress exceeds the prompt",
                      slot=slot.index, prompt_done=slot.prompt_done,
                      prompt_len=slot.request.prompt_len)
        elif slot.state is not SlotState.IDLE:
            _fail("scheduler-books", "an empty slot is not IDLE",
                  slot=slot.index, state=slot.state.value)
    keys = set(sched.tenants)
    if set(sched._queues) != keys or set(sched._deficit) != keys:
        _fail("scheduler-books",
              "tenant table / queues / DRR deficits diverged",
              tenants=sorted(keys), queues=sorted(sched._queues),
              deficits=sorted(sched._deficit))
    ring_members = [t for ring in sched._rr.values() for t in ring]
    if sorted(ring_members) != sorted(keys):
        _fail("scheduler-books",
              "tier rings do not cover each tenant exactly once",
              rings=ring_members, tenants=sorted(keys))


def check_router(router) -> None:
    """PodRouter-level joins: flight phases vs the pending deque vs the
    admit-hook page snapshots vs the front queue. (Worker engines check
    themselves inside their own step().)"""
    flights = router._flights
    phases = {"prefill", "pending", "decode"}
    pending_ids = {id(f) for f in router._pending}
    for f in flights.values():
        if f.phase not in phases:
            _fail("router-books", "unknown flight phase",
                  phase=f.phase, request_id=f.user.request_id)
        if f.user.done:
            _fail("router-books",
                  "a terminal request still has a live flight",
                  request_id=f.user.request_id,
                  status=f.user.status.value)
        if (f.phase == "pending") != (id(f) in pending_ids):
            _fail("router-books",
                  "flight phase and pending-buffer membership disagree",
                  request_id=f.user.request_id, phase=f.phase)
    # the backpressure bound stops NEW assignments, it is not a hard cap:
    # every already-assigned in-flight prefill may still finish and park
    # its shipment, so the true invariant adds the prefill capacity
    prefill_capacity = sum(len(w.scheduler.slots)
                           for w in router.prefill_workers)
    if len(router._pending) > router._max_pending + prefill_capacity:
        _fail("router-books",
              "pending shipments exceed the backpressure bound plus the "
              "in-flight prefill capacity", pending=len(router._pending),
              bound=router._max_pending, prefill_capacity=prefill_capacity)
    live_internals = {id(f.internal) for f in flights.values()
                      if f.phase == "prefill" and f.internal is not None}
    stale = [k for k in router._admit_pages if k not in live_internals]
    if stale:
        _fail("router-books",
              "admit-hook page snapshots outlive their prefill flights "
              "(the snapshot map would grow forever)",
              stale_entries=len(stale))
    from .scheduler import RequestStatus

    for r in router.scheduler.queue:
        if r.status is not RequestStatus.QUEUED:
            _fail("router-books",
                  "a front-queued request is not QUEUED",
                  request_id=r.request_id, status=r.status.value)


def check_distributed_router(router) -> None:
    """DistributedPodRouter cross-process joins: flight phases vs the
    pending/replay deques vs worker assignment vs worker liveness.
    Workers sanitize their own engines inside their own step(); these
    checks are the invariants only the router can see — in particular
    that NO flight rides a dead worker (the no-zombie rule: a lost
    worker's flights must all have been replayed) and that the worker
    table itself is coherent."""
    flights = router._flights
    phases = {"replay", "prefill", "pending", "decode"}
    pending_ids = set(router._pending)
    replay_ids = set(router._replay)
    for fid, f in flights.items():
        if f.flight_id != fid:
            _fail("droute-books", "flight table key != flight_id",
                  key=fid, flight_id=f.flight_id)
        if f.phase not in phases:
            _fail("droute-books", "unknown flight phase",
                  phase=f.phase, request_id=f.user.request_id)
        if f.user.done:
            _fail("droute-books",
                  "a terminal request still has a live flight",
                  request_id=f.user.request_id,
                  status=f.user.status.value)
        if f.attempt < 1:
            _fail("droute-books", "flight attempt below 1",
                  request_id=f.user.request_id, attempt=f.attempt)
        if (f.phase == "pending") != (fid in pending_ids):
            _fail("droute-books",
                  "flight phase and pending-buffer membership disagree",
                  request_id=f.user.request_id, phase=f.phase)
        if (f.phase == "replay") != (fid in replay_ids):
            _fail("droute-books",
                  "flight phase and replay-queue membership disagree",
                  request_id=f.user.request_id, phase=f.phase)
        if f.phase == "pending" and f.shipment is None:
            _fail("droute-books", "a pending flight holds no shipment",
                  request_id=f.user.request_id)
        if f.phase in ("prefill", "decode"):
            handle = router.workers.get(f.worker)
            if handle is None:
                _fail("droute-books",
                      "a flight is assigned to an unknown worker",
                      request_id=f.user.request_id, worker=f.worker)
            elif handle.lost:
                # THE no-zombie rule: losing a worker must replay every
                # flight it held, atomically with the loss
                _fail("droute-books",
                      "a flight still rides a LOST worker",
                      request_id=f.user.request_id, worker=f.worker,
                      phase=f.phase)
        else:
            if f.worker != -1:
                _fail("droute-books",
                      "a router-held flight names a worker",
                      request_id=f.user.request_id, phase=f.phase,
                      worker=f.worker)
    if len(router._by_user) != len(flights):
        _fail("droute-books",
              "user-index and flight table sizes diverged",
              by_user=len(router._by_user), flights=len(flights))
    for handle in router.workers.values():
        if handle.alive and handle.lost:
            _fail("droute-books",
                  "a worker is both alive and lost (zombie bookkeeping)",
                  worker=handle.worker_id)
    # the pending bound mirrors check_router's: assignment stops at
    # _max_pending but already-assigned prefills may still land, so the
    # hard cap adds the alive prefill-capable capacity
    prefill_capacity = sum(
        h.slots for h in router.workers.values() if h.alive)
    if len(router._pending) > router._max_pending + prefill_capacity:
        _fail("droute-books",
              "pending shipments exceed the backpressure bound plus the "
              "alive worker capacity", pending=len(router._pending),
              bound=router._max_pending, capacity=prefill_capacity)
    from .scheduler import RequestStatus

    for r in router.scheduler.queue:
        if r.status is not RequestStatus.QUEUED:
            _fail("droute-books",
                  "a front-queued request is not QUEUED",
                  request_id=r.request_id, status=r.status.value)
