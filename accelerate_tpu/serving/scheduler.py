"""Request lifecycle and slot scheduling for the serving engine.

Pure host-side logic — no jax in the hot methods — so policy is testable
without a model and the engine's device programs stay fixed-shape. The
scheduler owns:

- multi-tenant admission: per-tenant queues grouped into strict priority
  tiers (a tier-0 request always admits before a tier-1 one), with
  deficit-round-robin fairness *within* a tier — each tenant accrues
  quantum proportional to its weight and spends it on its head request's
  estimated service cost, so a chatty tenant cannot starve a quiet one
  and weights translate into long-run service shares;
- load shedding: a full queue or an over-long request REJECTS at submit
  (a reported status carrying a `retry_after_s` estimate, not an OOM
  three layers deeper); a queued request whose wait deadline lapses is
  shed with status EXPIRED; and — TTFT-SLO-aware admission — a queued
  request that can no longer meet its TTFT SLO *even if admitted this
  instant* is shed as a certain miss, and under queue pressure the
  predicted-miss victim is shed instead of the newest arrival;
- the slot table: admit into free slots, chunked-prefill progress,
  retirement on finish/cancel (slot reuse is a length reset — see
  serving/cache.py);
- the prefill/decode interleave policy: when both kinds of work exist the
  engine alternates one prefill chunk with one batched decode step, so a
  long prompt arriving mid-flight delays running streams by at most one
  chunk's latency instead of its whole prefill.

Everything here is host-side policy: tenants, tiers, SLO math, and DRR
bookkeeping never reach a traced value, so the engine's three compiled
programs are untouched by any scheduling decision.
"""

from __future__ import annotations

import enum
import itertools
import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import numpy as np


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"   # refused at submit (queue full / too long)
    EXPIRED = "expired"     # shed from the queue (deadline or certain SLO miss)
    CANCELLED = "cancelled"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's scheduling contract.

    `priority` is a strict tier (lower = more important: tier 0 empties
    before tier 1 sees a slot). `weight` is the tenant's deficit-round-
    robin share *within* its tier. `ttft_slo_s` is the default TTFT
    service objective for the tenant's requests — it drives SLO-aware
    shedding and the per-tenant attainment metrics; a per-request
    `slo_ttft_s` overrides it. `max_queue` caps this tenant's queued
    requests on top of the scheduler-wide bound (None = global only)."""

    name: str
    priority: int = 1
    weight: float = 1.0
    ttft_slo_s: float | None = None
    max_queue: int | None = None


DEFAULT_TENANT = "default"

# the machine-readable shed/reject vocabulary (Request.shed_code):
SHED_TOO_LONG = "too_long"                  # prompt+budget exceeds max_len
SHED_QUEUE_FULL = "queue_full"              # global queue bound hit
SHED_TENANT_QUEUE_FULL = "tenant_queue_full"  # per-tenant cap hit
SHED_DEADLINE = "deadline"                  # caller's queue-wait deadline
SHED_CERTAIN_MISS = "certain_miss"          # TTFT SLO unreachable even now
SHED_PRESSURE_VICTIM = "pressure_victim"    # worst-slack victim under pressure
SHED_DISPLACED = "displaced_by_tier"        # bumped by a higher-tier arrival
SHED_WORKER_DROP = "worker_drop"            # a pod worker dropped the request


class SlotState(enum.Enum):
    IDLE = "idle"
    PREFILL = "prefill"
    DECODE = "decode"


@dataclass(eq=False)
class Request:
    """One generation request and its observable state. The object returned
    by `Engine.submit` IS the handle: `tokens` fills as decode steps land,
    `status`/`done` report lifecycle, `metrics` carries per-request timing
    (TTFT, per-token latencies) once finished.

    eq=False: requests compare by identity. The generated __eq__ would
    compare the numpy `prompt` field element-wise, which makes
    `queue.remove(request)` / `request in queue` raise on any queue with
    depth > 1 — and two distinct requests with equal fields must never
    alias in the scheduler anyway."""

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    key: Any = None                      # per-request PRNG key (optional)
    eos_token_id: int | None = None
    deadline_s: float | None = None      # max queue wait before shedding
    tenant: str = DEFAULT_TENANT
    slo_ttft_s: float | None = None      # overrides the tenant's ttft_slo_s
    request_id: int = -1

    status: RequestStatus = RequestStatus.QUEUED
    reject_reason: str | None = None
    # machine-readable companion to reject_reason — one of the SHED_*
    # codes below. The HTTP layer puts THIS in the 429 envelope; the
    # prose reason is for humans reading logs
    shed_code: str | None = None
    retry_after_s: float | None = None   # backoff hint on REJECTED/EXPIRED
    # request tracing (telemetry.trace): trace_id is the id the server
    # returns as x-request-id; trace_sampled gates span recording (head
    # sampling — an unsampled request still keeps its id); span_id is the
    # pre-allocated root span children parent onto; trace_parent is the
    # inbound traceparent's span id (0 = we are the root)
    trace_id: Any = None
    trace_parent: Any = 0
    trace_sampled: bool = False
    span_id: int = 0
    # COW forking (Engine.fork): parent_id names the request this one was
    # forked from (None = not a fork); share_prompt marks a fork PARENT —
    # its full prompt pages are published into the prefix tree as prefill
    # completes them, so forks map the pages instead of re-prefilling
    parent_id: int | None = None
    share_prompt: bool = False
    tokens: list[int] = field(default_factory=list)
    # per-token logprob of each emitted token under the UNSCALED target
    # model (log-softmax of the raw logits at the token) — temperature-
    # independent, so greedy and sampled requests are comparable and
    # best_of can rank by true cumulative logprob
    logprobs: list[float] = field(default_factory=list)
    submitted_at: float = 0.0
    admitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    token_times: list[float] = field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def done(self) -> bool:
        return self.status in (RequestStatus.FINISHED, RequestStatus.REJECTED,
                               RequestStatus.EXPIRED, RequestStatus.CANCELLED)

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def cumulative_logprob(self) -> float | None:
        """Sum of the emitted tokens' model logprobs (None before any
        token carries one) — the best_of ranking score."""
        if not self.logprobs:
            return None
        return float(sum(self.logprobs))

    @property
    def slo_met(self) -> bool | None:
        """True/False once an SLO verdict exists; None when no SLO applies
        (or the request is still in flight before its first token)."""
        if self.slo_ttft_s is None:
            return None
        if self.first_token_at is not None:
            return self.ttft_s <= self.slo_ttft_s
        return False if self.done else None


@dataclass
class Slot:
    index: int
    state: SlotState = SlotState.IDLE
    request: Request | None = None
    prompt_done: int = 0   # prompt tokens prefilled so far (incl. reused)
    alloc: Any = None      # PageAllocation when a paged allocator is wired
    # speculative decoding: prompt tokens the DRAFT model has prefilled.
    # The draft never reuses cached pages (its K/V is a different model's),
    # so on a prefix hit it starts at 0 while prompt_done starts at the
    # reused length — the engine runs draft-only catch-up chunks first.
    draft_done: int = 0

    def free(self) -> None:
        self.state = SlotState.IDLE
        self.request = None
        self.prompt_done = 0
        self.alloc = None
        self.draft_done = 0


class Scheduler:
    """Admission control + slot assignment + prefill/decode interleave.

    With no `tenants` configured every request lands in the single
    "default" tenant at tier 1, and admission degenerates to exactly the
    FIFO this scheduler always had — existing single-tenant callers see
    identical behavior."""

    def __init__(
        self,
        num_slots: int,
        max_len: int,
        max_queue: int = 128,
        clock: Callable[[], float] = time.monotonic,
        allocator: Any = None,
        tenants: Iterable[TenantSpec] | dict[str, TenantSpec] | None = None,
        prefill_chunk: int = 32,
        drr_quantum: float = 16.0,
        max_tenants: int = 256,
    ):
        self.slots = [Slot(i) for i in range(num_slots)]
        self.max_len = max_len
        self.max_queue = max_queue
        self.clock = clock
        # optional paged-KV allocator (serving/cache.py PagedAllocator
        # protocol: allocate(request) -> alloc | None, release(slot,
        # finished)). Admission then ALSO requires pages: the policy head
        # waits while the pool is tight (no skip-ahead — small requests
        # must not starve a big one) and retirement returns pages.
        self.allocator = allocator
        self.prefill_chunk = max(1, prefill_chunk)
        self.drr_quantum = drr_quantum
        self.max_tenants = max_tenants
        if isinstance(tenants, dict):
            tenants = tenants.values()
        self.tenants: dict[str, TenantSpec] = {
            t.name: t for t in (tenants or ())}
        for t in self.tenants.values():
            if t.weight <= 0:
                raise ValueError(
                    f"tenant {t.name!r}: weight must be > 0 (got {t.weight})"
                    " — a zero-weight tenant would never accrue DRR credit")
        self.tenants.setdefault(DEFAULT_TENANT, TenantSpec(DEFAULT_TENANT))
        # one FIFO per tenant; admission order across them is strict
        # priority tiers, deficit-round-robin inside a tier
        self._queues: dict[str, deque[Request]] = {
            name: deque() for name in self.tenants}
        self._deficit: dict[str, float] = {name: 0.0 for name in self.tenants}
        self._rr: dict[int, deque[str]] = {}
        for name, spec in self.tenants.items():
            self._rr.setdefault(spec.priority, deque()).append(name)
        self._ids = itertools.count()
        self._last_was_prefill = False
        # EMA of one engine step's wall time — the unit the SLO/backlog
        # estimates are denominated in; fed by Engine.step via
        # note_step_time (0.0 until the first step = optimistic estimates,
        # so cold starts never shed)
        self.step_time_ema = 0.0
        self.rejected_full = 0
        self.rejected_too_long = 0
        self.expired = 0
        self.expired_slo = 0
        # every shed request lands here until the engine drains it into
        # metrics — victims shed inside submit() (pressure/displacement)
        # have no other path to observe_request
        self.shed_log: list[Request] = []

    # -- tenants / cost model ------------------------------------------------

    def _spec(self, name: str) -> TenantSpec:
        spec = self.tenants.get(name)
        if spec is None:
            # unknown tenants are admitted under a default-shaped contract
            # rather than crashing the data plane; the server layer decides
            # whether unknown tenants are a 401 instead. Auto-created
            # state is CAPPED: tenant names arrive off the wire, and
            # per-name queues/deficits/labeled series are otherwise an
            # unauthenticated unbounded-memory vector — past the cap,
            # unknown names collapse into the shared default tenant
            # (the request's tenant field is rewritten in submit()).
            if len(self.tenants) >= self.max_tenants:
                return self.tenants[DEFAULT_TENANT]
            spec = TenantSpec(name)
            self.tenants[name] = spec
            self._queues[name] = deque()
            self._deficit[name] = 0.0
            self._rr.setdefault(spec.priority, deque()).append(name)
        return spec

    def _cost(self, req: Request) -> float:
        """Estimated engine steps a request consumes end to end: its
        prefill chunks plus one decode step per budgeted token. The DRR
        currency — weights buy steps, not request counts, so tenants
        sending huge prompts pay for them."""
        chunks = math.ceil(max(0, req.prompt_len) / self.prefill_chunk)
        return float(chunks + req.max_new_tokens)

    def _prefill_cost(self, req: Request) -> float:
        """Steps until the request's FIRST token once admitted: prefill
        chunks, doubled for the decode steps the interleave policy runs
        between them (strict alternation)."""
        chunks = math.ceil(max(1, req.prompt_len) / self.prefill_chunk)
        return float(2 * chunks - 1)

    def effective_slo(self, req: Request) -> float | None:
        if req.slo_ttft_s is not None:
            return req.slo_ttft_s
        return self._spec(req.tenant).ttft_slo_s

    # -- admission -----------------------------------------------------------

    def submit(self, request: Request) -> Request:
        """Queue a request, or mark it REJECTED immediately: the contract is
        that overload is *reported* here (with a Retry-After estimate),
        never discovered as an OOM or an unbounded queue later.

        Under queue pressure the victim is SLO-chosen: if some queued
        request is already predicted to miss its TTFT SLO, shedding *it*
        frees the capacity — the doomed request was lost either way, the
        new one may still make it. Only when nobody is doomed does the
        newest arrival bounce."""
        spec = self._spec(request.tenant)
        if spec.name != request.tenant:
            # tenant-cap overflow: this request rides the default contract
            request.tenant = spec.name
        if request.slo_ttft_s is None:
            request.slo_ttft_s = spec.ttft_slo_s
        request.request_id = next(self._ids)
        request.submitted_at = self.clock()
        if request.prompt_len + request.max_new_tokens > self.max_len:
            request.status = RequestStatus.REJECTED
            request.reject_reason = (
                f"prompt_len({request.prompt_len}) + max_new_tokens"
                f"({request.max_new_tokens}) exceeds slot max_len"
                f"({self.max_len})"
            )
            request.shed_code = SHED_TOO_LONG
            self.rejected_too_long += 1
            return request
        tenant_q = self._queues[request.tenant]
        over_tenant = (spec.max_queue is not None
                       and len(tenant_q) >= spec.max_queue)
        if self.queue_depth >= self.max_queue or over_tenant:
            if not over_tenant and (self._shed_predicted_miss(request)
                                    or self._displace_lower_tier(request)):
                tenant_q.append(request)
                return request
            request.status = RequestStatus.REJECTED
            request.reject_reason = (
                f"tenant queue full (max_queue={spec.max_queue})"
                if over_tenant
                else f"queue full (max_queue={self.max_queue})")
            request.shed_code = (SHED_TENANT_QUEUE_FULL if over_tenant
                                 else SHED_QUEUE_FULL)
            request.retry_after_s = self.retry_after_estimate()
            self.rejected_full += 1
            return request
        tenant_q.append(request)
        return request

    def note_step_time(self, dt: float) -> None:
        """Fold one engine step's wall time into the EMA the SLO and
        Retry-After estimates are built from."""
        if dt <= 0.0:
            return
        self.step_time_ema = (dt if self.step_time_ema == 0.0
                              else 0.9 * self.step_time_ema + 0.1 * dt)

    def retry_after_estimate(self) -> float:
        """Coarse client backoff hint: the time the current backlog needs
        to drain through the slot lanes, clamped to something a client
        can act on."""
        backlog = sum(self._cost(r) for q in self._queues.values() for r in q)
        backlog += sum(self._remaining_steps(s) for s in self.slots
                       if s.state is not SlotState.IDLE)
        per_step = self.step_time_ema or 0.01
        est = backlog * per_step / max(1, len(self.slots))
        return round(min(max(est, 0.05), 60.0), 3)

    def _remaining_steps(self, slot: Slot) -> float:
        req = slot.request
        if req is None:
            return 0.0
        left_prompt = max(0, req.prompt_len - slot.prompt_done)
        chunks = math.ceil(left_prompt / self.prefill_chunk)
        return float(chunks + max(0, req.max_new_tokens - len(req.tokens)))

    def predicted_ttft(self, req: Request, now: float | None = None) -> float:
        """Estimated TTFT if the request stays queued: elapsed wait + the
        backlog ahead of it draining through the slot lanes + its own
        prefill. An *estimate* (slot retirements are stochastic), used to
        pick shedding victims — certain misses are decided by the lower
        bound in `shed_doomed`, not by this."""
        now = self.clock() if now is None else now
        ahead = 0.0
        my_tier = self._spec(req.tenant).priority
        for name, q in self._queues.items():
            tier = self.tenants[name].priority
            for other in q:
                if other is req:
                    continue
                if tier < my_tier or (tier == my_tier
                                      and other.request_id < req.request_id):
                    ahead += self._cost(other)
        running = sum(self._remaining_steps(s) for s in self.slots
                      if s.state is not SlotState.IDLE)
        per_step = self.step_time_ema
        wait = (ahead + running) * per_step / max(1, len(self.slots))
        return (now - req.submitted_at) + wait \
            + self._prefill_cost(req) * per_step

    # -- shedding ------------------------------------------------------------

    def _shed(self, req: Request, reason: str, now: float, code: str,
              slo_miss: bool = False) -> None:
        self._queues[req.tenant].remove(req)
        req.status = RequestStatus.EXPIRED
        req.reject_reason = reason
        req.shed_code = code
        req.retry_after_s = self.retry_after_estimate()
        req.finished_at = now
        self.expired += 1
        if slo_miss:
            self.expired_slo += 1
        self.shed_log.append(req)

    def drain_shed(self) -> list[Request]:
        """Shed requests not yet folded into metrics (engine-owned)."""
        out, self.shed_log = self.shed_log, []
        return out

    def shed_expired(self, now: float | None = None) -> list[Request]:
        """Drop queued requests whose wait deadline lapsed, plus the
        certain SLO misses: a request whose elapsed wait + *minimum*
        possible time-to-first-token (admitted this very step, nothing
        ahead) already exceeds its TTFT SLO cannot be saved — serving it
        would burn slot time on an answer the client has already written
        off, at the expense of requests that can still hit their SLO."""
        now = self.clock() if now is None else now
        shed = []
        for q in self._queues.values():
            for r in list(q):
                if (r.deadline_s is not None
                        and now - r.submitted_at > r.deadline_s):
                    shed.append((r, f"deadline_s={r.deadline_s} lapsed in "
                                 "queue", SHED_DEADLINE, False))
                    continue
                slo = self.effective_slo(r)
                if slo is None or self.step_time_ema == 0.0:
                    continue
                floor = (now - r.submitted_at
                         + self._prefill_cost(r) * self.step_time_ema)
                if floor > slo:
                    shed.append((r, f"certain TTFT SLO miss (slo={slo}s, "
                                 f"floor={floor:.3f}s)", SHED_CERTAIN_MISS,
                                 True))
        for r, reason, code, slo_miss in shed:
            self._shed(r, reason, now, code, slo_miss=slo_miss)
        return [r for r, _, _, _ in shed]

    def _shed_predicted_miss(self, newcomer: Request) -> bool:
        """Queue-pressure victim selection: shed the queued request most
        certainly headed for an SLO miss (worst predicted slack, ties to
        the lower tier) instead of bouncing the newcomer. Returns True
        when a victim was shed (a queue position is now free).

        One pass, not O(queue^2): this runs exactly at peak overload, on
        the same event loop that streams tokens, so the backlog ahead of
        each request comes from a prefix sum over the policy order
        ((tier, arrival)) instead of re-scanning the queue per request —
        the same slack predicted_ttft computes, at O(Q log Q + slots)."""
        now = self.clock()
        per_step = self.step_time_ema
        running = sum(self._remaining_steps(s) for s in self.slots
                      if s.state is not SlotState.IDLE)
        ordered = sorted(
            ((self.tenants[name].priority, r.request_id, r)
             for name, q in self._queues.items() for r in q))
        worst, worst_slack = None, 0.0
        ahead = 0.0
        for _, _, r in ordered:
            slo = self.effective_slo(r)
            if slo is not None:
                wait = (ahead + running) * per_step / max(1, len(self.slots))
                predicted = ((now - r.submitted_at) + wait
                             + self._prefill_cost(r) * per_step)
                slack = slo - predicted
                if slack < worst_slack:
                    worst, worst_slack = r, slack
            ahead += self._cost(r)
        if worst is None:
            return False
        self._shed(worst, "shed under pressure: predicted TTFT "
                   f"{worst_slack:+.3f}s past SLO", now,
                   SHED_PRESSURE_VICTIM, slo_miss=True)
        return True

    def _displace_lower_tier(self, newcomer: Request) -> bool:
        """Strict priority must hold at the queue boundary too: a full
        queue of tier-1 work must not 429 a tier-0 arrival. The newest
        queued request of the strictly-lowest tier below the newcomer's
        is shed (it has waited least, so it loses the least invested
        time — and with a TTFT SLO it is also the likeliest eventual
        miss once a higher-tier request is jumping it anyway)."""
        my_tier = self._spec(newcomer.tenant).priority
        worst = None
        for name, q in self._queues.items():
            tier = self.tenants[name].priority
            if tier <= my_tier or not q:
                continue
            cand = q[-1]
            if (worst is None
                    or tier > self.tenants[worst.tenant].priority
                    or (tier == self.tenants[worst.tenant].priority
                        and cand.request_id > worst.request_id)):
                worst = cand
        if worst is None:
            return False
        self._shed(worst, f"displaced by a tier-{my_tier} arrival under "
                   "queue pressure", self.clock(), SHED_DISPLACED,
                   slo_miss=self.effective_slo(worst) is not None)
        return True

    # -- DRR tier selection ---------------------------------------------------

    def _select_tenant(self) -> str | None:
        """The tenant whose head request is next by policy: strict tiers,
        deficit-round-robin within the winning tier. Deficits accrue in
        whole quantum rounds until some head is affordable — bounded,
        since costs are bounded by max_len."""
        occupied = [p for p in sorted(self._rr)
                    if any(self._queues[t] for t in self._rr[p])]
        if not occupied:
            return None
        tier = occupied[0]
        order = self._rr[tier]
        active = [t for t in order if self._queues[t]]
        for name in order:
            if not self._queues[name]:
                # classic DRR: an empty queue forfeits its deficit, so
                # idle tenants can't bank unbounded credit
                self._deficit[name] = 0.0
        while True:
            for name in list(order):
                if (self._queues[name] and self._deficit[name]
                        >= self._cost(self._queues[name][0])):
                    return name
            for name in active:
                self._deficit[name] += (self.drr_quantum
                                        * self.tenants[name].weight)

    def _pop_selected(self, name: str) -> Request:
        req = self._queues[name].popleft()
        self._deficit[name] -= self._cost(req)
        if not self._queues[name]:
            self._deficit[name] = 0.0
        # rotate the round-robin ring so the served tenant goes last —
        # equal-weight tenants alternate instead of one head-of-ring
        # tenant draining first
        ring = self._rr[self.tenants[name].priority]
        if ring[0] == name:
            ring.rotate(-1)
        return req

    def tenant_priority(self, name: str) -> int:
        """A tenant's strict tier (lower = more important) — policy
        input for the engine's cache-aware admission hold (a request
        never waits on a lower-tier leader's prefill)."""
        return self._spec(name).priority

    def _group_prefix_sharers(self, name: str, head: Request) -> None:
        """Cache-aware admission ordering (ISSUE 16): when `head` is
        admitted, stable-promote the queued requests of the SAME tenant
        that share its full shareable prefix to the queue front, so the
        wave admits while the pages are hottest (held a few steps by
        the engine's dedup hold, then mapped — one prefill or one
        swap-in serves all of them). Bounded on purpose: reordering
        never crosses a tenant (tiers, DRR deficits, and per-tenant
        caps are untouched — DRR charges costs per pop regardless of
        intra-tenant order) and is skipped entirely without a
        prefix-caching allocator."""
        alloc = self.allocator
        if alloc is None or not getattr(alloc, "prefix_cache", False):
            return
        k = ((head.prompt_len - 1) // alloc.page_size) * alloc.page_size
        q = self._queues.get(name)
        if q is None or k <= 0 or len(q) < 2:
            return
        key = np.ascontiguousarray(head.prompt[:k], np.int32).tobytes()
        sharers = [
            r for r in q
            if r.prompt_len > k
            and np.ascontiguousarray(r.prompt[:k], np.int32).tobytes() == key
        ]
        if not sharers:
            return
        sharer_ids = {id(r) for r in sharers}
        rest = [r for r in q if id(r) not in sharer_ids]
        q.clear()
        q.extend(sharers)
        q.extend(rest)

    def admissions(self, now: float | None = None) -> list[tuple[Slot, Request]]:
        """Pop queued requests into free slots in policy order (tiers,
        then DRR). With a paged allocator, admission also reserves the
        request's worst-case pages; the policy head blocks admission
        while the pool is tight (pages free up as running slots retire).
        A prefix hit starts `prompt_done` at the reused length — prefill
        covers only the uncached suffix."""
        now = self.clock() if now is None else now
        # in-flight grouping: a request prefilling RIGHT NOW is the
        # hottest possible head (its pages publish as it goes) — promote
        # its queued same-tenant sharers so they admit behind it and
        # ride the engine's dedup hold, instead of behind unrelated
        # traffic whose admission could evict the shared pages.
        # Idempotent: once the sharers lead the queue this is a no-op.
        for slot in self.slots:
            if slot.state is SlotState.PREFILL and slot.request is not None:
                self._group_prefix_sharers(slot.request.tenant, slot.request)
        admitted = []
        for slot in self.slots:
            if slot.state is not SlotState.IDLE:
                continue
            name = self._select_tenant()
            if name is None:
                break
            alloc = None
            if self.allocator is not None:
                alloc = self.allocator.allocate(self._queues[name][0])
                if alloc is None:
                    break
                # attach the reservation to its slot IMMEDIATELY: any
                # raise between allocate and attachment would strand the
                # pages outside both the slot table and the free list
                # (the ATP201 exception-window class)
                slot.alloc = alloc
            req = self._pop_selected(name)
            self._group_prefix_sharers(name, req)
            req.status = RequestStatus.RUNNING
            req.admitted_at = now
            slot.request = req
            slot.state = SlotState.PREFILL
            slot.prompt_done = alloc.reused_len if alloc is not None else 0
            admitted.append((slot, req))
        return admitted

    def adopt_running(self, request: Request, alloc: Any = None,
                      now: float | None = None) -> Slot | None:
        """Attach an externally prepared request straight into a free
        slot, already in DECODE state with its whole prompt accounted as
        done — the pod page-shipping path (serving/pod): prefill happened
        on another worker and the KV pages were installed by the caller,
        so this slot's next step is its first decode. Bypasses the queue
        on purpose (the pod router owns admission policy; this scheduler
        only owns the slot table). Returns the slot, or None when no slot
        is free — the caller must NOT have allocated pages yet in that
        case, or must release them."""
        now = self.clock() if now is None else now
        for slot in self.slots:
            if slot.state is SlotState.IDLE:
                if request.request_id < 0:
                    request.request_id = next(self._ids)
                request.status = RequestStatus.RUNNING
                if request.admitted_at is None:
                    request.admitted_at = now
                slot.request = request
                slot.state = SlotState.DECODE
                slot.alloc = alloc
                slot.prompt_done = request.prompt_len
                return slot
        return None

    # -- the interleave policy ----------------------------------------------

    def next_action(self) -> tuple[str, Any] | None:
        """('prefill', slot) | ('decode', [slots]) | None.

        Strict alternation when both kinds of work exist: a decode step
        always runs between two prefill chunks, so running streams see at
        most one chunk of extra latency however long the arriving prompt.
        """
        prefilling = [s for s in self.slots if s.state is SlotState.PREFILL]
        decoding = [s for s in self.slots if s.state is SlotState.DECODE]
        if prefilling:
            # FIFO by admission, NOT by slot index: under sustained load a
            # freed low-index slot re-fills every step, and picking by
            # index would starve a long prompt mid-prefill in a higher
            # slot forever (accepted request, unbounded TTFT)
            oldest = min(prefilling, key=lambda s: s.request.admitted_at)
        if prefilling and (not decoding or not self._last_was_prefill):
            self._last_was_prefill = True
            return ("prefill", oldest)
        if decoding:
            self._last_was_prefill = False
            return ("decode", decoding)
        return None

    # -- progress notes from the engine --------------------------------------

    def note_prefill_chunk(self, slot: Slot, n_tokens: int) -> bool:
        """Advance a slot's prefill by `n_tokens` real prompt tokens;
        returns True when the prompt is fully prefilled (the chunk that
        also produced the request's first token)."""
        slot.prompt_done += n_tokens
        if slot.prompt_done >= slot.request.prompt_len:
            slot.state = SlotState.DECODE
            return True
        return False

    def note_token(self, slot: Slot, token: int,
                   now: float | None = None,
                   logprob: float | None = None) -> bool:
        """Record one generated token (and, when the engine computed it,
        the token's model logprob); retire the slot when the request hits
        max_new_tokens or its EOS. Returns True on retirement."""
        now = self.clock() if now is None else now
        req = slot.request
        req.tokens.append(int(token))
        if logprob is not None:
            req.logprobs.append(float(logprob))
        req.token_times.append(now)
        if req.first_token_at is None:
            req.first_token_at = now
        eos = (req.eos_token_id is not None
               and int(token) == req.eos_token_id)
        if eos or len(req.tokens) >= req.max_new_tokens:
            req.status = RequestStatus.FINISHED
            req.finished_at = now
            self._retire(slot, finished=True)
            return True
        return False

    def _retire(self, slot: Slot, finished: bool) -> None:
        """Free a slot, returning its pages first when paged: a finished
        request's full prompt pages go back into the prefix tree (reuse),
        a cancelled one's pages to the free list."""
        if self.allocator is not None and slot.alloc is not None:
            self.allocator.release(slot, finished=finished)
        slot.free()

    def finish_early(self, request: Request) -> bool:
        """Retire a RUNNING request as FINISHED before its token budget —
        the server's stop-sequence path: the client got a complete answer,
        so the request must count as finished (TTFT/latency samples and
        all), and its prompt pages go back to the prefix tree exactly as
        a natural finish would."""
        if request.done:
            return False
        for slot in self.slots:
            if slot.request is request:
                request.status = RequestStatus.FINISHED
                request.finished_at = self.clock()
                self._retire(slot, finished=True)
                return True
        return False

    def cancel(self, request: Request) -> bool:
        """Cancel a queued or running request; no-op on finished ones."""
        if request.done:
            return False
        q = self._queues.get(request.tenant)
        if q is not None and request in q:
            q.remove(request)
            request.status = RequestStatus.CANCELLED
            request.finished_at = self.clock()
            return True
        for slot in self.slots:
            if slot.request is request:
                self._retire(slot, finished=False)
                request.status = RequestStatus.CANCELLED
                request.finished_at = self.clock()
                return True
        return False

    # -- introspection --------------------------------------------------------

    @property
    def queue(self) -> list[Request]:
        """All queued requests in submit order (introspection/back-compat
        view; mutation goes through submit/cancel/shed)."""
        out = [r for q in self._queues.values() for r in q]
        out.sort(key=lambda r: r.request_id)
        return out

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def tenant_queue_depth(self, tenant: str) -> int:
        q = self._queues.get(tenant)
        return len(q) if q is not None else 0

    @property
    def live_slots(self) -> int:
        return sum(1 for s in self.slots if s.state is not SlotState.IDLE)

    def has_work(self) -> bool:
        return self.queue_depth > 0 or self.live_slots > 0

    def running(self) -> Iterable[Request]:
        return [s.request for s in self.slots if s.request is not None]

    def debug_state(self) -> dict:
        """JSON-safe policy-state snapshot for `/debug/scheduler` and
        incident bundles: per-tenant queue depths + DRR deficits, tier
        membership, the step-time EMA every SLO estimate is denominated
        in, and the shed counters. Read-only; numbers only."""
        tenants = {}
        for name, spec in self.tenants.items():
            tenants[name] = {
                "priority": spec.priority,
                "weight": spec.weight,
                "ttft_slo_s": spec.ttft_slo_s,
                "max_queue": spec.max_queue,
                "queue_depth": len(self._queues.get(name, ())),
                "drr_deficit": self._deficit.get(name, 0.0),
            }
        return {
            "queue_depth": self.queue_depth,
            "max_queue": self.max_queue,
            "live_slots": self.live_slots,
            "num_slots": len(self.slots),
            "step_time_ema_s": self.step_time_ema,
            "drr_quantum": self.drr_quantum,
            "rejected_full": self.rejected_full,
            "rejected_too_long": self.rejected_too_long,
            "expired": self.expired,
            "expired_slo": self.expired_slo,
            "tiers": {str(p): list(ring) for p, ring in self._rr.items()},
            "tenants": tenants,
        }
