"""Request lifecycle and slot scheduling for the serving engine.

Pure host-side logic — no jax in the hot methods — so policy is testable
without a model and the engine's device programs stay fixed-shape. The
scheduler owns:

- the FIFO admission queue with load shedding: a full queue or an
  over-long request REJECTS at submit (a reported status, not an OOM three
  layers deeper), and a queued request whose deadline lapses before a slot
  frees is shed with status EXPIRED;
- the slot table: admit into free slots, chunked-prefill progress,
  retirement on finish/cancel (slot reuse is a length reset — see
  serving/cache.py);
- the prefill/decode interleave policy: when both kinds of work exist the
  engine alternates one prefill chunk with one batched decode step, so a
  long prompt arriving mid-flight delays running streams by at most one
  chunk's latency instead of its whole prefill.
"""

from __future__ import annotations

import enum
import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import numpy as np


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"   # refused at submit (queue full / too long)
    EXPIRED = "expired"     # shed from the queue past its deadline
    CANCELLED = "cancelled"


class SlotState(enum.Enum):
    IDLE = "idle"
    PREFILL = "prefill"
    DECODE = "decode"


@dataclass(eq=False)
class Request:
    """One generation request and its observable state. The object returned
    by `Engine.submit` IS the handle: `tokens` fills as decode steps land,
    `status`/`done` report lifecycle, `metrics` carries per-request timing
    (TTFT, per-token latencies) once finished.

    eq=False: requests compare by identity. The generated __eq__ would
    compare the numpy `prompt` field element-wise, which makes
    `queue.remove(request)` / `request in queue` raise on any queue with
    depth > 1 — and two distinct requests with equal fields must never
    alias in the scheduler anyway."""

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    key: Any = None                      # per-request PRNG key (optional)
    eos_token_id: int | None = None
    deadline_s: float | None = None      # max queue wait before shedding
    request_id: int = -1

    status: RequestStatus = RequestStatus.QUEUED
    reject_reason: str | None = None
    tokens: list[int] = field(default_factory=list)
    submitted_at: float = 0.0
    admitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    token_times: list[float] = field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def done(self) -> bool:
        return self.status in (RequestStatus.FINISHED, RequestStatus.REJECTED,
                               RequestStatus.EXPIRED, RequestStatus.CANCELLED)

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


@dataclass
class Slot:
    index: int
    state: SlotState = SlotState.IDLE
    request: Request | None = None
    prompt_done: int = 0   # prompt tokens prefilled so far (incl. reused)
    alloc: Any = None      # PageAllocation when a paged allocator is wired

    def free(self) -> None:
        self.state = SlotState.IDLE
        self.request = None
        self.prompt_done = 0
        self.alloc = None


class Scheduler:
    """Admission control + slot assignment + prefill/decode interleave."""

    def __init__(
        self,
        num_slots: int,
        max_len: int,
        max_queue: int = 128,
        clock: Callable[[], float] = time.monotonic,
        allocator: Any = None,
    ):
        self.slots = [Slot(i) for i in range(num_slots)]
        self.max_len = max_len
        self.max_queue = max_queue
        self.queue: deque[Request] = deque()
        self.clock = clock
        # optional paged-KV allocator (serving/cache.py PagedAllocator
        # protocol: allocate(request) -> alloc | None, release(slot,
        # finished)). Admission then ALSO requires pages: the FIFO head
        # waits while the pool is tight (no skip-ahead — small requests
        # must not starve a big one) and retirement returns pages.
        self.allocator = allocator
        self._ids = itertools.count()
        self._last_was_prefill = False
        self.rejected_full = 0
        self.rejected_too_long = 0
        self.expired = 0

    # -- admission -----------------------------------------------------------

    def submit(self, request: Request) -> Request:
        """Queue a request, or mark it REJECTED immediately: the contract is
        that overload is *reported* here, never discovered as an OOM or an
        unbounded queue later."""
        request.request_id = next(self._ids)
        request.submitted_at = self.clock()
        if request.prompt_len + request.max_new_tokens > self.max_len:
            request.status = RequestStatus.REJECTED
            request.reject_reason = (
                f"prompt_len({request.prompt_len}) + max_new_tokens"
                f"({request.max_new_tokens}) exceeds slot max_len"
                f"({self.max_len})"
            )
            self.rejected_too_long += 1
            return request
        if len(self.queue) >= self.max_queue:
            request.status = RequestStatus.REJECTED
            request.reject_reason = f"queue full (max_queue={self.max_queue})"
            self.rejected_full += 1
            return request
        self.queue.append(request)
        return request

    def shed_expired(self, now: float | None = None) -> list[Request]:
        """Drop queued requests whose deadline lapsed before admission."""
        now = self.clock() if now is None else now
        shed = [
            r for r in self.queue
            if r.deadline_s is not None and now - r.submitted_at > r.deadline_s
        ]
        for r in shed:
            self.queue.remove(r)
            r.status = RequestStatus.EXPIRED
            r.reject_reason = f"deadline_s={r.deadline_s} lapsed in queue"
            r.finished_at = now
            self.expired += 1
        return shed

    def admissions(self, now: float | None = None) -> list[tuple[Slot, Request]]:
        """Pop queued requests into free slots (FIFO). With a paged
        allocator, admission also reserves the request's worst-case
        pages; the FIFO head blocks admission while the pool is tight
        (pages free up as running slots retire). A prefix hit starts
        `prompt_done` at the reused length — prefill covers only the
        uncached suffix."""
        now = self.clock() if now is None else now
        admitted = []
        for slot in self.slots:
            if slot.state is not SlotState.IDLE or not self.queue:
                continue
            alloc = None
            if self.allocator is not None:
                alloc = self.allocator.allocate(self.queue[0])
                if alloc is None:
                    break
            req = self.queue.popleft()
            req.status = RequestStatus.RUNNING
            req.admitted_at = now
            slot.request = req
            slot.state = SlotState.PREFILL
            slot.alloc = alloc
            slot.prompt_done = alloc.reused_len if alloc is not None else 0
            admitted.append((slot, req))
        return admitted

    # -- the interleave policy ----------------------------------------------

    def next_action(self) -> tuple[str, Any] | None:
        """('prefill', slot) | ('decode', [slots]) | None.

        Strict alternation when both kinds of work exist: a decode step
        always runs between two prefill chunks, so running streams see at
        most one chunk of extra latency however long the arriving prompt.
        """
        prefilling = [s for s in self.slots if s.state is SlotState.PREFILL]
        decoding = [s for s in self.slots if s.state is SlotState.DECODE]
        if prefilling:
            # FIFO by admission, NOT by slot index: under sustained load a
            # freed low-index slot re-fills every step, and picking by
            # index would starve a long prompt mid-prefill in a higher
            # slot forever (accepted request, unbounded TTFT)
            oldest = min(prefilling, key=lambda s: s.request.admitted_at)
        if prefilling and (not decoding or not self._last_was_prefill):
            self._last_was_prefill = True
            return ("prefill", oldest)
        if decoding:
            self._last_was_prefill = False
            return ("decode", decoding)
        return None

    # -- progress notes from the engine --------------------------------------

    def note_prefill_chunk(self, slot: Slot, n_tokens: int) -> bool:
        """Advance a slot's prefill by `n_tokens` real prompt tokens;
        returns True when the prompt is fully prefilled (the chunk that
        also produced the request's first token)."""
        slot.prompt_done += n_tokens
        if slot.prompt_done >= slot.request.prompt_len:
            slot.state = SlotState.DECODE
            return True
        return False

    def note_token(self, slot: Slot, token: int,
                   now: float | None = None) -> bool:
        """Record one generated token; retire the slot when the request
        hits max_new_tokens or its EOS. Returns True on retirement."""
        now = self.clock() if now is None else now
        req = slot.request
        req.tokens.append(int(token))
        req.token_times.append(now)
        if req.first_token_at is None:
            req.first_token_at = now
        eos = (req.eos_token_id is not None
               and int(token) == req.eos_token_id)
        if eos or len(req.tokens) >= req.max_new_tokens:
            req.status = RequestStatus.FINISHED
            req.finished_at = now
            self._retire(slot, finished=True)
            return True
        return False

    def _retire(self, slot: Slot, finished: bool) -> None:
        """Free a slot, returning its pages first when paged: a finished
        request's full prompt pages go back into the prefix tree (reuse),
        a cancelled one's pages to the free list."""
        if self.allocator is not None and slot.alloc is not None:
            self.allocator.release(slot, finished=finished)
        slot.free()

    def cancel(self, request: Request) -> bool:
        """Cancel a queued or running request; no-op on finished ones."""
        if request.done:
            return False
        if request in self.queue:
            self.queue.remove(request)
            request.status = RequestStatus.CANCELLED
            request.finished_at = self.clock()
            return True
        for slot in self.slots:
            if slot.request is request:
                self._retire(slot, finished=False)
                request.status = RequestStatus.CANCELLED
                request.finished_at = self.clock()
                return True
        return False

    # -- introspection --------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def live_slots(self) -> int:
        return sum(1 for s in self.slots if s.state is not SlotState.IDLE)

    def has_work(self) -> bool:
        return bool(self.queue) or self.live_slots > 0

    def running(self) -> Iterable[Request]:
        return [s.request for s in self.slots if s.request is not None]
