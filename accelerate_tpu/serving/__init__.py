"""Continuous-batching serving: request-level scheduling over ONE compiled
decode program.

The training half of the repo compiles one program and feeds it batches;
this package does the same for inference traffic: `Engine` multiplexes many
concurrent generation requests through a paged KV pool (`PagedKVCache`,
with cross-request prompt-prefix reuse via the host-side `PrefixIndex`
radix tree), a `Scheduler` that admits/sheds/retires requests and
interleaves chunked prefill with batched decode, and per-request streaming
with TTFT/per-token metrics. `SlotKVCache` is the simpler contiguous
slot-dense layout the pool generalizes. See docs/serving.md.
"""

from .cache import (
    PagedAllocator,
    PagedKVCache,
    PageAllocation,
    PagePool,
    PrefixIndex,
    SlotKVCache,
)
from .engine import Engine, EngineConfig
from .metrics import ServingMetrics
from .scheduler import (
    Request,
    RequestStatus,
    Scheduler,
    Slot,
    SlotState,
    TenantSpec,
)

# unambiguous name for the top-level package namespace
ServingEngine = Engine

__all__ = [
    "Engine",
    "ServingEngine",
    "EngineConfig",
    "SlotKVCache",
    "PagedKVCache",
    "PagedAllocator",
    "PageAllocation",
    "PagePool",
    "PrefixIndex",
    "ServingMetrics",
    "Scheduler",
    "Request",
    "RequestStatus",
    "Slot",
    "SlotState",
    "TenantSpec",
]
