"""Continuous-batching serving: request-level scheduling over ONE compiled
decode program.

The training half of the repo compiles one program and feeds it batches;
this package does the same for inference traffic: `Engine` multiplexes many
concurrent generation requests through a paged KV pool (`PagedKVCache`,
with cross-request prompt-prefix reuse via the host-side `PrefixIndex`
radix tree), a `Scheduler` that admits/sheds/retires requests and
interleaves chunked prefill with batched decode, and per-request streaming
with TTFT/per-token metrics. `SlotKVCache` is the simpler contiguous
slot-dense layout the pool generalizes. See docs/serving.md.

The `serving.pod` subpackage scales this past one chip: `sharded_engine`
runs one engine tensor-parallel over a mesh (SPMD), `PodEngine` splits
prefill from decode across worker groups shipping KV pages (MPMD) —
both behind this same API. Imported lazily here so single-device
serving never pays for it.
"""

from .cache import (
    PagedAllocator,
    PagedKVCache,
    PageAllocation,
    PagePool,
    PrefixIndex,
    SlotKVCache,
)
from .engine import Engine, EngineConfig
from .metrics import ServingMetrics
from .sanitizer import SanitizerViolation
from .scheduler import (
    Request,
    RequestStatus,
    Scheduler,
    Slot,
    SlotState,
    TenantSpec,
)

# unambiguous name for the top-level package namespace
ServingEngine = Engine

_POD_EXPORTS = {
    "PodConfig", "PodEngine", "PodRouter", "KVPageShipment",
    "PageTransport", "sharded_engine", "tensor_mesh",
}


def __getattr__(name):
    # pod layer resolved lazily: `from accelerate_tpu.serving import
    # PodEngine` works, but plain single-device serving never imports
    # the sharding/transfer machinery
    if name in _POD_EXPORTS:
        from . import pod

        return getattr(pod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Engine",
    "ServingEngine",
    "EngineConfig",
    "SlotKVCache",
    "PagedKVCache",
    "PagedAllocator",
    "PageAllocation",
    "PagePool",
    "PrefixIndex",
    "ServingMetrics",
    "SanitizerViolation",
    "Scheduler",
    "Request",
    "RequestStatus",
    "Slot",
    "SlotState",
    "TenantSpec",
    # pod layer (lazy — see __getattr__)
    "PodConfig",
    "PodEngine",
    "PodRouter",
    "KVPageShipment",
    "PageTransport",
    "sharded_engine",
    "tensor_mesh",
]
