"""Serving metrics: per-request latency distributions + engine gauges.

The serving numbers that matter are distributional (a mean TTFT hides the
p99 a shed request would have seen). Distributions live in the shared
`telemetry.StreamingHistogram` sketches — bounded memory however long the
server runs, exact counts/sums, mergeable across hosts — registered on a
`telemetry.MetricsRegistry` so the same series the `summary()` dict
reports are also served by the Prometheus endpoint and the JSONL
snapshot writer. Engine-level gauges (slot occupancy, queue depth,
tokens/sec) are sampled once per engine step. The summary is a flat
str -> float dict, so it drops straight into the existing tracking layer
(`GeneralTracker.log`) and into `bench.py`'s one-line JSON.
"""

from __future__ import annotations

import numpy as np

from ..telemetry.registry import MetricsRegistry, StreamingHistogram
from .scheduler import Request


def _percentiles(hist: StreamingHistogram, name: str) -> dict[str, float]:
    if not hist.count:
        return {}
    return {
        f"{name}_p50_ms": hist.quantile(0.5) * 1e3,
        f"{name}_p99_ms": hist.quantile(0.99) * 1e3,
        f"{name}_mean_ms": hist.mean * 1e3,
    }


class ServingMetrics:
    """Aggregates finished requests + per-step engine gauges.

    All series are registry-backed; pass the engine's registry so the
    exporters see them, or omit it for a self-contained instance."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = r = registry or MetricsRegistry()
        self.ttft_s = r.histogram("serving_ttft_seconds")
        self.tpot_s = r.histogram("serving_per_token_seconds")
        self.queue_wait_s = r.histogram("serving_queue_wait_seconds")
        self.occupancy = r.histogram("serving_slot_occupancy")
        self.queue_depth = r.histogram("serving_queue_depth")
        self._c_finished = r.counter("serving_requests_finished_total")
        self._c_cancelled = r.counter("serving_requests_cancelled_total")
        self._c_rejected = r.counter("serving_requests_rejected_total")
        self._c_expired = r.counter("serving_requests_expired_total")
        self._c_tokens = r.counter("serving_tokens_out_total")
        self._c_decode = r.counter("serving_decode_steps_total")
        self._c_prefill = r.counter("serving_prefill_chunks_total")
        # paged-KV prefix reuse: lookups = admissions, hits = admissions
        # that mapped >= 1 cached page; prompt-token totals make the
        # cached-token fraction derivable from counters alone
        self._c_prefix_lookups = r.counter("serving_prefix_lookups_total")
        self._c_prefix_hits = r.counter("serving_prefix_hits_total")
        self._c_prefix_tokens = r.counter("serving_prefix_tokens_reused_total")
        self._c_prompt_tokens = r.counter("serving_prompt_tokens_total")
        self._c_evictions = r.counter("serving_page_evictions_total")
        # hierarchical KV (ISSUE 16): prefix hits split by the tier that
        # served them (an hbm hit mapped pages in place, a host hit paid
        # a swap-in), swap traffic in pages both directions, the host
        # tier's occupancy, and the swap-in latency the admission paid
        self._c_prefix_hits_hbm = r.counter("serving_prefix_hits_hbm_total")
        self._c_prefix_hits_host = r.counter(
            "serving_prefix_hits_host_total")
        self._c_swap_in = r.counter("serving_swap_in_pages_total")
        self._c_swap_out = r.counter("serving_swap_out_pages_total")
        # in-flight prefill dedup (cache-aware scheduling): followers
        # that waited on a leader's publish instead of duplicating it
        self._c_dedup = r.counter("serving_prefix_dedup_hits_total")
        self.swap_in_s = r.histogram("serving_swap_in_seconds")
        self._g_host_pages = r.gauge("serving_host_tier_pages_in_use")
        self._g_host_bytes = r.gauge("serving_host_tier_bytes_in_use")
        # speculative decoding (ISSUE 12): drafted vs accepted proposal
        # totals per slot-step; the accept-rate gauge is their running
        # ratio and tokens-per-decode-step is the headline lever (how
        # many tokens one MXU-occupying step now commits)
        self._c_spec_drafted = r.counter("serving_spec_drafted_tokens_total")
        self._c_spec_accepted = r.counter(
            "serving_spec_accepted_tokens_total")
        self._g_spec_accept_rate = r.gauge("serving_spec_accept_rate")
        self._g_tokens_per_step = r.gauge("serving_tokens_per_decode_step")
        self._g_queue_depth = r.gauge("serving_queue_depth_current")
        self._g_occupancy = r.gauge("serving_slot_occupancy_current")
        self._g_tokens_per_sec = r.gauge("serving_tokens_per_sec")
        self._g_pages_in_use = r.gauge("serving_pages_in_use")
        self._g_pages_free = r.gauge("serving_pages_free")
        # KV HBM actually held by live slots + cached prefixes (pages in
        # use x per-page bytes incl. int8 scales) — the series that shows
        # kv_dtype="int8" halving the footprint for the same page count
        self._g_kv_bytes = r.gauge("serving_kv_bytes_in_use")
        # goodput: useful generated-token device-time / wall-time — the
        # engine computes it from the cost table's sampled device times
        # (Engine._goodput) and keeps this gauge live per step
        self._g_goodput = r.gauge("serving_goodput")
        self._c_decode_path: dict = {}
        self.started_at: float | None = None
        self.stopped_at: float | None = None

    # -- per-tenant labeled series -------------------------------------------
    # created lazily at first observation, so single-tenant engines keep
    # exactly the series they always had; the registry's get-or-create
    # makes repeat lookups cheap and exporter-visible automatically

    def _tenant_hist(self, name: str, tenant: str) -> StreamingHistogram:
        return self.registry.histogram(name, tenant=tenant)

    def _tenant_counter(self, name: str, tenant: str):
        return self.registry.counter(name, tenant=tenant)

    # counters read back as ints for the summary / engine bookkeeping
    @property
    def finished(self) -> int:
        return int(self._c_finished.value)

    @property
    def cancelled(self) -> int:
        return int(self._c_cancelled.value)

    @property
    def rejected(self) -> int:
        return int(self._c_rejected.value)

    @property
    def expired(self) -> int:
        return int(self._c_expired.value)

    @property
    def tokens_out(self) -> int:
        return int(self._c_tokens.value)

    @property
    def decode_steps(self) -> int:
        return int(self._c_decode.value)

    @property
    def prefill_chunks(self) -> int:
        return int(self._c_prefill.value)

    @property
    def prefix_lookups(self) -> int:
        return int(self._c_prefix_lookups.value)

    @property
    def prefix_hits(self) -> int:
        return int(self._c_prefix_hits.value)

    @property
    def prefix_tokens_reused(self) -> int:
        return int(self._c_prefix_tokens.value)

    @property
    def prompt_tokens(self) -> int:
        return int(self._c_prompt_tokens.value)

    @property
    def page_evictions(self) -> int:
        return int(self._c_evictions.value)

    @property
    def prefix_hits_hbm(self) -> int:
        return int(self._c_prefix_hits_hbm.value)

    @property
    def prefix_hits_host(self) -> int:
        return int(self._c_prefix_hits_host.value)

    @property
    def swap_in_pages(self) -> int:
        return int(self._c_swap_in.value)

    @property
    def swap_out_pages(self) -> int:
        return int(self._c_swap_out.value)

    @property
    def prefix_dedup_hits(self) -> int:
        return int(self._c_dedup.value)

    def note_decode_step(self, path: str = "dense") -> None:
        """`path` is which decode attention op served the step —
        "kernel" (Pallas paged attention) or "dense" (gather reference)
        — so a config regression that silently drops the kernel shows
        up as the labeled counter going flat. The labeled counter is
        cached per path (this runs in the per-token host hot loop —
        same once-resolved pattern as every sibling series)."""
        self._c_decode.inc()
        ctr = self._c_decode_path.get(path)
        if ctr is None:
            ctr = self._c_decode_path[path] = self.registry.counter(
                "serving_decode_path_total", path=path)
        ctr.inc()

    @property
    def spec_drafted_tokens(self) -> int:
        return int(self._c_spec_drafted.value)

    @property
    def spec_accepted_tokens(self) -> int:
        return int(self._c_spec_accepted.value)

    def note_speculation(self, drafted: int, accepted: int) -> None:
        """One slot's speculative-step outcome: `drafted` proposals
        (always draft_k), `accepted` of them survived verification."""
        self._c_spec_drafted.inc(drafted)
        self._c_spec_accepted.inc(accepted)
        total = self.spec_drafted_tokens
        if total:
            self._g_spec_accept_rate.set(self.spec_accepted_tokens / total)

    def note_prefill_chunk(self) -> None:
        self._c_prefill.inc()

    def note_admission(self, prompt_len: int, reused_len: int,
                       host_pages: int = 0) -> None:
        """One admitted request's prefix-cache outcome. `host_pages` is
        how many of the reused pages were swapped in from the host tier
        — any makes this a host-tier hit (the admission paid a swap-in),
        else an HBM hit."""
        self._c_prefix_lookups.inc()
        self._c_prompt_tokens.inc(prompt_len)
        if reused_len > 0:
            self._c_prefix_hits.inc()
            self._c_prefix_tokens.inc(reused_len)
            if host_pages > 0:
                self._c_prefix_hits_host.inc()
            else:
                self._c_prefix_hits_hbm.inc()

    def note_page_evictions(self, n: int) -> None:
        self._c_evictions.inc(n)

    def note_swap_out(self, n: int) -> None:
        self._c_swap_out.inc(n)

    def note_swap_in(self, n: int, seconds: float) -> None:
        self._c_swap_in.inc(n)
        self.swap_in_s.record(seconds)

    def note_dedup_hit(self) -> None:
        self._c_dedup.inc()

    def set_host_tier_gauges(self, pages: int, bytes_in_use: int) -> None:
        self._g_host_pages.set(pages)
        self._g_host_bytes.set(bytes_in_use)

    def set_goodput(self, value: float) -> None:
        self._g_goodput.set(value)

    def set_page_gauges(self, in_use: int, free: int,
                        bytes_in_use: int | None = None) -> None:
        self._g_pages_in_use.set(in_use)
        self._g_pages_free.set(free)
        if bytes_in_use is not None:
            self._g_kv_bytes.set(bytes_in_use)

    def observe_step(self, live_slots: int, num_slots: int,
                     queue_depth: int) -> None:
        occ = live_slots / max(1, num_slots)
        self.occupancy.record(occ)
        self.queue_depth.record(queue_depth)
        self._g_occupancy.set(occ)
        self._g_queue_depth.set(queue_depth)
        if self.decode_steps:
            self._g_tokens_per_step.set(self.tokens_out / self.decode_steps)
        if (self.started_at is not None and self.stopped_at is not None
                and self.stopped_at > self.started_at):
            self._g_tokens_per_sec.set(
                self.tokens_out / (self.stopped_at - self.started_at))

    def observe_request(self, req: Request) -> None:
        """Fold one terminal request into the aggregates — both the
        engine-wide series and the `{tenant=...}`-labeled copies the
        per-tier SLO dashboards (and serve_bench --tenants) read."""
        tenant = getattr(req, "tenant", "default")
        # OpenMetrics exemplar: every latency sample carries its request's
        # trace id, so a bad p99 bucket on the scrape links straight to
        # the one trace that landed in it (ISSUE 8)
        ex = getattr(req, "trace_id", None)
        ex = str(ex) if ex is not None else None
        if req.status.value == "finished":
            self._c_finished.inc()
            self._tenant_counter("serving_requests_finished_total",
                                 tenant).inc()
            self._c_tokens.inc(len(req.tokens))
            if req.ttft_s is not None:
                self.ttft_s.record(req.ttft_s, exemplar=ex)
                self._tenant_hist("serving_ttft_seconds",
                                  tenant).record(req.ttft_s, exemplar=ex)
            if req.admitted_at is not None:
                self.queue_wait_s.record(req.admitted_at - req.submitted_at)
            # per-token latency: gaps between consecutive decode tokens
            # (TTFT is its own metric; the first gap is excluded)
            tpot_t = self._tenant_hist("serving_per_token_seconds", tenant)
            for g in np.diff(req.token_times):
                self.tpot_s.record(float(g), exemplar=ex)
                tpot_t.record(float(g), exemplar=ex)
        elif req.status.value == "cancelled":
            self._c_cancelled.inc()
        elif req.status.value == "rejected":
            self._c_rejected.inc()
            self._tenant_counter("serving_requests_rejected_total",
                                 tenant).inc()
        elif req.status.value == "expired":
            self._c_expired.inc()
            self._tenant_counter("serving_requests_expired_total",
                                 tenant).inc()
        # SLO attainment: every terminal request with an SLO gets a
        # verdict — finished-in-time counts as met; late, shed, and
        # rejected count as missed. A client cancel BEFORE first token is
        # excluded (the client walked away; no serving verdict exists).
        # The attainment a tier reports is met/total from these series.
        met = req.slo_met
        if (req.status.value == "cancelled"
                and req.first_token_at is None):
            met = None
        if met is not None:
            self._tenant_counter("serving_slo_total", tenant).inc()
            if met:
                self._tenant_counter("serving_slo_met_total", tenant).inc()

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {
            "requests_finished": float(self.finished),
            "requests_rejected": float(self.rejected),
            "requests_expired": float(self.expired),
            "requests_cancelled": float(self.cancelled),
            "tokens_out": float(self.tokens_out),
            "decode_steps": float(self.decode_steps),
            "prefill_chunks": float(self.prefill_chunks),
            "prefix_hits": float(self.prefix_hits),
            "prefix_tokens_reused": float(self.prefix_tokens_reused),
            "page_evictions": float(self.page_evictions),
            "pages_in_use": float(self._g_pages_in_use.value),
            "pages_free": float(self._g_pages_free.value),
            "kv_bytes_in_use": float(self._g_kv_bytes.value),
        }
        if self.decode_steps:
            out["tokens_per_decode_step"] = (
                self.tokens_out / self.decode_steps)
        if self.spec_drafted_tokens:
            out["spec_drafted_tokens"] = float(self.spec_drafted_tokens)
            out["spec_accepted_tokens"] = float(self.spec_accepted_tokens)
            out["spec_accept_rate"] = (
                self.spec_accepted_tokens / self.spec_drafted_tokens)
        if self.prefix_lookups:
            out["prefix_hit_rate"] = self.prefix_hits / self.prefix_lookups
        if self.prefix_hits:
            out["prefix_hits_hbm"] = float(self.prefix_hits_hbm)
            out["prefix_hits_host"] = float(self.prefix_hits_host)
        if self.prefix_dedup_hits:
            out["prefix_dedup_hits"] = float(self.prefix_dedup_hits)
        if self.swap_out_pages or self.swap_in_pages:
            out["swap_out_pages"] = float(self.swap_out_pages)
            out["swap_in_pages"] = float(self.swap_in_pages)
            out["host_tier_pages_in_use"] = float(self._g_host_pages.value)
            out["host_tier_bytes_in_use"] = float(self._g_host_bytes.value)
            out.update(_percentiles(self.swap_in_s, "swap_in"))
        if self.prompt_tokens:
            out["cached_token_fraction"] = (
                self.prefix_tokens_reused / self.prompt_tokens)
        out.update(_percentiles(self.ttft_s, "ttft"))
        out.update(_percentiles(self.tpot_s, "per_token"))
        out.update(_percentiles(self.queue_wait_s, "queue_wait"))
        if self.occupancy.count:
            out["slot_occupancy_mean"] = self.occupancy.mean
        if self.queue_depth.count:
            out["queue_depth_mean"] = self.queue_depth.mean
            out["queue_depth_max"] = self.queue_depth.max
        if (self.started_at is not None and self.stopped_at is not None
                and self.stopped_at > self.started_at):
            out["tokens_per_sec"] = self.tokens_out / (
                self.stopped_at - self.started_at)
        return out

    def tenant_summary(self) -> dict[str, dict[str, float]]:
        """Per-tenant view built from the labeled series: TTFT/per-token
        percentiles, terminal counts, and SLO attainment (met/total).
        Keys are tenant names; only tenants that produced observations
        appear."""
        out: dict[str, dict[str, float]] = {}
        for kind, name, labels, metric in self.registry.items():
            tenant = dict(labels).get("tenant")
            if tenant is None:
                continue
            row = out.setdefault(tenant, {})
            if kind == "histogram" and metric.count:
                base = {"serving_ttft_seconds": "ttft",
                        "serving_per_token_seconds": "per_token"}.get(name)
                if base:
                    row.update(_percentiles(metric, base))
            elif kind == "counter":
                short = name.replace("serving_", "").replace("_total", "")
                row[short] = float(metric.value)
        for row in out.values():
            total = row.get("slo", 0.0)
            if total:
                row["slo_attainment"] = row.get("slo_met", 0.0) / total
        return out
