"""Serving metrics: per-request latency distributions + engine gauges.

The serving numbers that matter are distributional (a mean TTFT hides the
p99 a shed request would have seen), so the aggregator keeps raw samples
and summarizes to percentiles. Engine-level gauges (slot occupancy, queue
depth) are sampled once per engine step. The summary is a flat
str -> float dict, so it drops straight into the existing tracking layer
(`GeneralTracker.log`) and into `bench.py`'s one-line JSON.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from .scheduler import Request

# Raw-sample cap: a long-lived server steps forever, and unbounded sample
# lists grow by O(steps + tokens) — percentiles are computed over the most
# recent window instead (counters stay exact and lifetime-cumulative).
MAX_SAMPLES = 100_000


def _window() -> deque[float]:
    return deque(maxlen=MAX_SAMPLES)


def _percentiles(samples: "deque[float]", name: str) -> dict[str, float]:
    if not samples:
        return {}
    arr = np.asarray(samples, dtype=np.float64)
    return {
        f"{name}_p50_ms": float(np.percentile(arr, 50) * 1e3),
        f"{name}_p99_ms": float(np.percentile(arr, 99) * 1e3),
        f"{name}_mean_ms": float(arr.mean() * 1e3),
    }


@dataclass
class ServingMetrics:
    """Aggregates finished requests + per-step engine gauges."""

    ttft_s: deque[float] = field(default_factory=_window)
    tpot_s: deque[float] = field(default_factory=_window)  # time per output token
    queue_wait_s: deque[float] = field(default_factory=_window)
    occupancy: deque[float] = field(default_factory=_window)
    queue_depth: deque[int] = field(default_factory=_window)
    finished: int = 0
    cancelled: int = 0
    rejected: int = 0
    expired: int = 0
    tokens_out: int = 0
    decode_steps: int = 0
    prefill_chunks: int = 0
    started_at: float | None = None
    stopped_at: float | None = None

    def observe_step(self, live_slots: int, num_slots: int,
                     queue_depth: int) -> None:
        self.occupancy.append(live_slots / max(1, num_slots))
        self.queue_depth.append(queue_depth)

    def observe_request(self, req: Request) -> None:
        """Fold one terminal request into the aggregates."""
        if req.status.value == "finished":
            self.finished += 1
            self.tokens_out += len(req.tokens)
            if req.ttft_s is not None:
                self.ttft_s.append(req.ttft_s)
            if req.admitted_at is not None:
                self.queue_wait_s.append(req.admitted_at - req.submitted_at)
            # per-token latency: gaps between consecutive decode tokens
            # (TTFT is its own metric; the first gap is excluded)
            gaps = np.diff(req.token_times)
            self.tpot_s.extend(float(g) for g in gaps)
        elif req.status.value == "cancelled":
            self.cancelled += 1
        elif req.status.value == "rejected":
            self.rejected += 1
        elif req.status.value == "expired":
            self.expired += 1

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {
            "requests_finished": float(self.finished),
            "requests_rejected": float(self.rejected),
            "requests_expired": float(self.expired),
            "requests_cancelled": float(self.cancelled),
            "tokens_out": float(self.tokens_out),
            "decode_steps": float(self.decode_steps),
            "prefill_chunks": float(self.prefill_chunks),
        }
        out.update(_percentiles(self.ttft_s, "ttft"))
        out.update(_percentiles(self.tpot_s, "per_token"))
        out.update(_percentiles(self.queue_wait_s, "queue_wait"))
        if self.occupancy:
            out["slot_occupancy_mean"] = float(np.mean(self.occupancy))
        if self.queue_depth:
            out["queue_depth_mean"] = float(np.mean(self.queue_depth))
            out["queue_depth_max"] = float(np.max(self.queue_depth))
        if (self.started_at is not None and self.stopped_at is not None
                and self.stopped_at > self.started_at):
            out["tokens_per_sec"] = self.tokens_out / (
                self.stopped_at - self.started_at)
        return out
