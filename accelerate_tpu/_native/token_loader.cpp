// Native token-corpus data loader: mmap + shuffled sharded sampling +
// multi-threaded ordered prefetch.
//
// TPU-native replacement for the host-side input machinery the reference
// delegates to torch DataLoader worker processes and torch-xla's
// MpDeviceLoader background threads (ref data_loader.py:518-559,
// SURVEY.md §2.1 "Data loader layer"): tokenized corpora are memory-mapped
// (no read amplification, page cache shared across processes), samples are
// fixed-length windows, each epoch is a seeded permutation sharded across
// hosts, and producer threads assemble batches ahead of the training step so
// the host never stalls the device. Exposed through a C ABI consumed by
// ctypes (native/__init__.py); semantics mirrored by the pure-Python
// fallback so environments without a toolchain behave identically.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread token_loader.cpp -o libatl.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

enum DType { DT_U16 = 0, DT_I32 = 1, DT_U32 = 2 };

struct Corpus {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t bytes = 0;
  int dtype = DT_I32;
  long sample_len = 0;   // tokens per sample window
  long num_tokens = 0;
  long num_samples = 0;
};

size_t elem_size(int dtype) { return dtype == DT_U16 ? 2 : 4; }

struct Slot {
  std::vector<int32_t> buf;
  long batch_id = -1;
  bool ready = false;
};

struct Loader {
  Corpus* corpus = nullptr;
  long batch = 0;
  bool shuffle = true;
  uint64_t seed = 0;
  int rank = 0, world = 1;
  bool drop_last = true;
  int threads = 2;
  int depth = 4;

  // epoch state
  std::vector<long> order;       // this shard's sample indices
  long num_batches = 0;
  std::vector<Slot> slots;
  std::vector<std::thread> pool;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::atomic<long> next_claim{0};
  long next_consume = 0;
  bool stopping = false;

  ~Loader() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_prod.notify_all();
    cv_cons.notify_all();
    for (auto& t : pool)
      if (t.joinable()) t.join();
    pool.clear();
    stopping = false;
  }

  void fill_batch(long b, Slot& slot) {
    const Corpus& c = *corpus;
    const long L = c.sample_len;
    slot.buf.resize(batch * L);
    const long base = b * batch;
    const long avail = (long)order.size();
    for (long i = 0; i < batch; ++i) {
      // wraparound padding for a short final batch (even_batches semantics)
      const long idx = order[(base + i) % avail];
      const uint8_t* src = c.data + (size_t)idx * L * elem_size(c.dtype);
      int32_t* dst = slot.buf.data() + i * L;
      if (c.dtype == DT_I32) {
        std::memcpy(dst, src, L * 4);
      } else if (c.dtype == DT_U16) {
        const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
        for (long t = 0; t < L; ++t) dst[t] = (int32_t)s[t];
      } else {
        const uint32_t* s = reinterpret_cast<const uint32_t*>(src);
        for (long t = 0; t < L; ++t) dst[t] = (int32_t)s[t];
      }
    }
  }

  void producer() {
    for (;;) {
      const long b = next_claim.fetch_add(1);
      if (b >= num_batches) return;
      Slot& slot = slots[b % depth];
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_prod.wait(lk, [&] { return stopping || b - next_consume < depth; });
        if (stopping) return;
      }
      fill_batch(b, slot);
      {
        std::lock_guard<std::mutex> lk(mu);
        slot.batch_id = b;
        slot.ready = true;
      }
      cv_cons.notify_all();
    }
  }

  // SplitMix64: trivially portable, reproduced bit-for-bit by the Python
  // fallback (native/__init__.py) so mixed native/fallback fleets compute
  // IDENTICAL permutations — host shards stay disjoint either way.
  static uint64_t splitmix64(uint64_t& s) {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }

  void start_epoch(long epoch) {
    stop();
    const Corpus& c = *corpus;
    // deterministic epoch order, identical on every host; shard by stride
    std::vector<long> all(c.num_samples);
    for (long i = 0; i < c.num_samples; ++i) all[i] = i;
    if (shuffle) {
      uint64_t s = seed ^ ((uint64_t)epoch * 0xD1B54A32D192ED03ull);
      for (long i = c.num_samples - 1; i > 0; --i) {
        const long j = (long)(splitmix64(s) % (uint64_t)(i + 1));
        std::swap(all[i], all[j]);
      }
    }
    // every rank takes exactly ceil(n/world) samples (wraparound fill), so
    // all hosts run the same number of batches — SPMD lockstep
    const long per = (c.num_samples + world - 1) / world;
    order.clear();
    order.reserve(per);
    for (long i = 0; i < per; ++i)
      order.push_back(all[(rank + i * world) % c.num_samples]);
    const long n = per;
    num_batches = drop_last ? n / batch : (n + batch - 1) / batch;
    slots.assign(depth, Slot{});
    next_claim.store(0);
    next_consume = 0;
    const int t = (int)std::max<long>(1, std::min<long>(threads, num_batches));
    for (int i = 0; i < t; ++i) pool.emplace_back([this] { producer(); });
  }

  // 0 = batch written, 1 = epoch exhausted
  int next(int32_t* out) {
    if (next_consume >= num_batches) return 1;
    const long b = next_consume;
    Slot& slot = slots[b % depth];
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_cons.wait(lk, [&] { return slot.ready && slot.batch_id == b; });
    }
    std::memcpy(out, slot.buf.data(), slot.buf.size() * 4);
    {
      std::lock_guard<std::mutex> lk(mu);
      slot.ready = false;
      next_consume = b + 1;
    }
    cv_prod.notify_all();
    return 0;
  }
};

}  // namespace

extern "C" {

void* atl_open(const char* path, int dtype_code, long sample_len) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (p == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(p, st.st_size, MADV_WILLNEED);
  auto* c = new Corpus;
  c->fd = fd;
  c->data = static_cast<const uint8_t*>(p);
  c->bytes = st.st_size;
  c->dtype = dtype_code;
  c->sample_len = sample_len;
  c->num_tokens = (long)(st.st_size / elem_size(dtype_code));
  c->num_samples = sample_len > 0 ? c->num_tokens / sample_len : 0;
  return c;
}

long atl_num_samples(void* corpus) {
  return corpus ? static_cast<Corpus*>(corpus)->num_samples : -1;
}

long atl_num_tokens(void* corpus) {
  return corpus ? static_cast<Corpus*>(corpus)->num_tokens : -1;
}

void atl_close(void* corpus) {
  auto* c = static_cast<Corpus*>(corpus);
  if (!c) return;
  if (c->data) munmap(const_cast<uint8_t*>(c->data), c->bytes);
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

void* atl_loader_new(void* corpus, long batch, int shuffle, uint64_t seed,
                     int rank, int world, int drop_last, int threads,
                     int depth) {
  if (!corpus || batch <= 0 || world <= 0 || rank < 0 || rank >= world)
    return nullptr;
  auto* l = new Loader;
  l->corpus = static_cast<Corpus*>(corpus);
  l->batch = batch;
  l->shuffle = shuffle != 0;
  l->seed = seed;
  l->rank = rank;
  l->world = world;
  l->drop_last = drop_last != 0;
  l->threads = threads > 0 ? threads : 2;
  l->depth = depth > 0 ? depth : 4;
  return l;
}

long atl_loader_batches_per_epoch(void* loader) {
  if (!loader) return -1;
  auto* l = static_cast<Loader*>(loader);
  const long n = (l->corpus->num_samples + l->world - 1) / l->world;
  return l->drop_last ? n / l->batch : (n + l->batch - 1) / l->batch;
}

void atl_loader_start_epoch(void* loader, long epoch) {
  if (loader) static_cast<Loader*>(loader)->start_epoch(epoch);
}

int atl_loader_next(void* loader, int32_t* out) {
  return loader ? static_cast<Loader*>(loader)->next(out) : -1;
}

void atl_loader_free(void* loader) { delete static_cast<Loader*>(loader); }

}  // extern "C"
