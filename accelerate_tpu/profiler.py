"""Profiling / tracing subsystem.

The reference has NO first-class profiler (SURVEY.md §5: only Megatron timers
and benchmark-side psutil helpers, ref utils/megatron_lm.py:1018-1026,
benchmarks/measures_util.py). This module makes tracing first-class for TPU:

- `profile(...)`: context manager around `jax.profiler` producing a
  TensorBoard/Perfetto/XProf trace of XLA execution.
- `annotate(...)`: named host-side region that shows up on the trace timeline.
- `StepTimer`: wall-clock per-step timing with warmup skipping; reports
  steps/sec, tokens/sec and MFU against the chip's peak FLOPs.
- `device_memory_stats()` / `live_array_bytes()`: HBM introspection
  (replaces ref utils/memory.py's psutil/torch.cuda views).

MFU math: a causal-LM training step costs ~6 FLOPs per parameter per token
(fwd 2 + bwd 4), plus attention ~12*L*H*S^2 per sequence when
`attention=True` — the standard accounting from the scaling literature.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

import jax

from .telemetry.registry import StreamingHistogram
from .utils.constants import TPU_PEAK_FLOPS


@contextlib.contextmanager
def profile(logdir: str = "/tmp/accelerate_tpu_trace",
            host_tracer_level: int = 2) -> Iterator[None]:
    """Capture an XLA execution trace viewable in TensorBoard/Perfetto."""
    # ProfileOptions only exists in newer jax; older runtimes take no options
    options_cls = getattr(jax.profiler, "ProfileOptions", None)
    if options_cls is not None:
        options = options_cls()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=options)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region on the trace timeline (and under jit, in the HLO)."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_stats(device=None) -> dict[str, int]:
    """Per-device memory stats (bytes): HBM in use / limit where the backend
    reports them; empty dict on backends without stats (CPU)."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats()
    return dict(stats) if stats else {}


def live_array_bytes() -> int:
    """Total bytes of live jax.Array shards resident on this process's
    devices (counts every replica — a fully replicated array on 8 local
    devices costs 8x its logical size in HBM)."""
    total = 0
    for arr in jax.live_arrays():
        try:
            total += sum(s.data.nbytes for s in arr.addressable_shards)
        except Exception:
            total += arr.nbytes
    return total


def peak_flops_per_chip(device=None) -> float:
    """Peak bf16 FLOPs/s for this chip generation (public specs table)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, flops in TPU_PEAK_FLOPS.items():
        if key in kind:
            return flops
    return 0.0


def causal_lm_train_flops(n_params: int, tokens: int,
                          num_layers: int = 0, hidden_size: int = 0,
                          seq_len: int = 0, attention: bool = True) -> float:
    """FLOPs for one training step over `tokens` tokens (6ND + attention)."""
    flops = 6.0 * n_params * tokens
    if attention and num_layers and seq_len:
        # 12 * L * h * S per token (fwd+bwd of QK^T and AV)
        flops += 12.0 * num_layers * hidden_size * seq_len * tokens
    return flops


def causal_lm_infer_flops(n_params: int, tokens: int,
                          num_layers: int = 0, hidden_size: int = 0,
                          kv_len: int = 0, attention: bool = True) -> float:
    """FLOPs to DECODE `tokens` tokens (forward only — no 6ND here):
    ~2 FLOPs per parameter per token for the weight matmuls, plus the
    paged-attention term — each new token attends over `kv_len` cached
    positions, costing ~4 * L * h * kv_len FLOPs (QK^T and AV, 2 each;
    GQA shrinks the cache read, not the query-side FLOPs, so `hidden_size`
    stays the full model width). This is the accounting the serving cost
    table's analytic fallback and decode-MFU meters use — reusing the
    training 6ND formula for decode overstates FLOPs 3x and hides how
    idle the MXU actually is."""
    flops = 2.0 * n_params * tokens
    if attention and num_layers and kv_len:
        flops += 4.0 * num_layers * hidden_size * kv_len * tokens
    return flops


@dataclass
class StepTimer:
    """Per-step timing + throughput/MFU meter, with host-overhead breakdown.

    Usage::

        timer = StepTimer(flops_per_step=..., tokens_per_step=...)
        it = iter(loader)
        while True:
            with timer.input_stall():      # time blocked on the pipeline
                batch = next(it, None)
            if batch is None:
                break
            with timer.dispatch():         # host-side cost of the step call
                state, metrics = step(state, batch)
            timer.tick(state)          # blocks on `state` to time honestly
        print(timer.summary())

    The two context managers isolate the overheads the device never sees:
    `dispatch()` wraps the python `step(...)` call — on an async backend
    (TPU) the call returns as soon as XLA execution is enqueued, so its
    wall time IS the per-step host dispatch cost (pytree flatten, sharding
    checks, argument processing), and a cached dispatch path shows up as
    microsecond readings. On the CPU backend execution is largely
    synchronous inside the call, so the reading absorbs device compute and
    only upper-bounds the host share. `input_stall()` wraps the
    `next(loader)` call — nonzero readings mean the device finished before
    its next batch was ready (input-bound step). Both respect
    `warmup_steps`.

    Samples land in bounded-memory streaming histograms
    (`telemetry.StreamingHistogram`) rather than raw lists: means stay
    exact (tracked sum/count) for a run of ANY length, and `summary()`
    reports tail latency (`step_time_p50_s`/`step_time_p99_s`) from the
    sketch. Pass a `telemetry.MetricsRegistry` as `registry` to publish
    the series (`<name>_time_seconds`, `<name>_dispatch_seconds`,
    `<name>_input_stall_seconds`) through the shared export surface
    (Prometheus endpoint, JSONL snapshots, multi-host aggregation).
    """

    flops_per_step: float = 0.0
    tokens_per_step: int = 0
    warmup_steps: int = 2          # compile + first dispatch excluded
    peak_flops: float | None = None
    num_chips: int | None = None
    registry: Any = None           # telemetry.MetricsRegistry | None
    name: str = "step"             # series prefix when registry-backed
    _last: float | None = None
    _seen: int = 0
    _dispatch_seen: int = 0
    _stall_seen: int = 0
    # wall window spanning exactly the recorded (post-warmup) steps:
    # goodput = useful step-time / wall-time over this window
    _window_start: float | None = None
    _window_end: float | None = None
    # the very first tick: window_start - first_tick is the warmup
    # (compile + first dispatch) wall time, the "compile" taxonomy bucket
    _first_tick: float | None = None
    # stall taxonomy: seconds per overhead kind (tagged overhead()
    # windows) and per externally attributed cause (note_lost)
    _overhead_kinds: dict = field(default_factory=dict, repr=False)
    _attributed: dict = field(default_factory=dict, repr=False)
    _step_hist: StreamingHistogram = field(default=None, repr=False)  # type: ignore[assignment]
    _dispatch_hist: StreamingHistogram = field(default=None, repr=False)  # type: ignore[assignment]
    _stall_hist: StreamingHistogram = field(default=None, repr=False)  # type: ignore[assignment]
    _overhead_hist: StreamingHistogram = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self) -> None:
        make = (self.registry.histogram if self.registry is not None
                else StreamingHistogram)
        if self._step_hist is None:
            self._step_hist = make(f"{self.name}_time_seconds")
        if self._dispatch_hist is None:
            self._dispatch_hist = make(f"{self.name}_dispatch_seconds")
        if self._stall_hist is None:
            self._stall_hist = make(f"{self.name}_input_stall_seconds")
        if self._overhead_hist is None:
            self._overhead_hist = make(f"{self.name}_overhead_seconds")

    def reset(self) -> None:
        """Zero the recorded samples (and warmup progress) in place. With
        a registry, the series OBJECTS are shared by name — a second timer
        with the same (registry, name) continues the same series unless
        reset; the exporter keeps serving the zeroed series either way."""
        for hist in (self._step_hist, self._dispatch_hist, self._stall_hist,
                     self._overhead_hist):
            hist.reset()
        self._last = None
        self._seen = self._dispatch_seen = self._stall_seen = 0
        self._window_start = self._window_end = None
        self._first_tick = None
        self._overhead_kinds.clear()
        self._attributed.clear()

    def tick(self, block_on: Any = None) -> float | None:
        """Record one step boundary; returns this step's seconds (or None
        during warmup). Pass the step's output pytree so timing waits for the
        device to finish (`jax.block_until_ready`)."""
        if block_on is not None:
            jax.block_until_ready(block_on)
        now = time.perf_counter()
        if self._first_tick is None:
            self._first_tick = now
        elapsed = None
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup_steps:
                elapsed = now - self._last
                self._step_hist.record(elapsed)
                self._window_end = now
        if self._seen <= self.warmup_steps:
            # this tick starts the first post-warmup interval: the
            # goodput window opens here, so warmup/compile never counts
            # as lost wall time
            self._window_start = now
        self._last = now
        return elapsed

    @contextlib.contextmanager
    def dispatch(self) -> Iterator[None]:
        """Time the host-side dispatch of one step (wrap the `step(...)`
        call). The first `warmup_steps` readings are excluded (compile +
        first dispatch), mirroring `tick`."""
        t0 = time.perf_counter()
        yield
        self._dispatch_seen += 1
        if self._dispatch_seen > self.warmup_steps:
            self._dispatch_hist.record(time.perf_counter() - t0)

    @contextlib.contextmanager
    def input_stall(self) -> Iterator[None]:
        """Time spent blocked waiting on the input pipeline (wrap the
        `next(loader)` call)."""
        t0 = time.perf_counter()
        yield
        self._stall_seen += 1
        if self._stall_seen > self.warmup_steps:
            self._stall_hist.record(time.perf_counter() - t0)

    @contextlib.contextmanager
    def overhead(self, kind: str | None = None) -> Iterator[None]:
        """Mark non-step wall time the loop KNOWS about (a checkpoint
        save, an eval pass, a log flush) so `goodput` can subtract it.
        Tick-to-tick intervals tile the wall clock, so unmarked work
        between ticks is indistinguishable from step time — this marker
        is how a training loop makes its goodput honest::

            with timer.overhead("checkpoint_stage"):
                accelerator.save_state(path, async_save=True)

        `kind` tags the window for `stall_taxonomy()` ("checkpoint_stage",
        "checkpoint_drain", "eval", ...); untagged windows bucket under
        "other".
        """
        t0 = time.perf_counter()
        yield
        elapsed = time.perf_counter() - t0
        self._overhead_hist.record(elapsed)
        key = kind or "other"
        self._overhead_kinds[key] = self._overhead_kinds.get(key, 0.0) + elapsed

    def note_lost(self, kind: str, seconds: float) -> None:
        """Attribute externally-diagnosed lost time (e.g. the straggler
        monitor's slowest-host excess) into the taxonomy WITHOUT touching
        goodput: that time already sits inside measured step intervals —
        this labels its cause, it does not subtract it twice."""
        self._attributed[kind] = (
            self._attributed.get(kind, 0.0) + float(seconds))

    def stall_taxonomy(self) -> dict[str, float]:
        """Where the wall clock went, in seconds over the goodput window:
        `step` (useful), `input` (pipeline stalls), one entry per tagged
        overhead kind (`checkpoint_stage`, `checkpoint_drain`, `other`,
        ...), `compile` (warmup wall time BEFORE the window opened —
        attribution only, the goodput window already excludes it), plus
        externally attributed causes (`straggler`, via `note_lost`).
        Empty before any step records."""
        if not self._step_hist.count or self._window_start is None:
            return {}
        stall = self._stall_hist.sum if self._stall_hist.count else 0.0
        overhead = self._overhead_hist.sum if self._overhead_hist.count else 0.0
        out = {
            "step": max(0.0, self._step_hist.sum - stall - overhead),
            "input": stall,
        }
        for kind, sec in self._overhead_kinds.items():
            out[kind] = out.get(kind, 0.0) + sec
        if self._first_tick is not None \
                and self._window_start > self._first_tick:
            out["compile"] = self._window_start - self._first_tick
        for kind, sec in self._attributed.items():
            out[kind] = out.get(kind, 0.0) + sec
        return out

    @property
    def host_dispatch_us(self) -> float:
        """Mean host-dispatch microseconds per (post-warmup) step."""
        if not self._dispatch_hist.count:
            return float("nan")
        return 1e6 * self._dispatch_hist.mean

    @property
    def input_stall_us(self) -> float:
        """Mean microseconds per (post-warmup) step spent waiting on input."""
        if not self._stall_hist.count:
            return float("nan")
        return 1e6 * self._stall_hist.mean

    @property
    def steps_recorded(self) -> int:
        return self._step_hist.count

    @property
    def mean_step_time(self) -> float:
        if not self._step_hist.count:
            return float("nan")
        return self._step_hist.mean

    @property
    def steps_per_sec(self) -> float:
        mean = self.mean_step_time
        return 1.0 / mean if mean and mean == mean else float("nan")

    @property
    def tokens_per_sec(self) -> float:
        return self.steps_per_sec * self.tokens_per_step

    @property
    def goodput(self) -> float:
        """Useful step-time / wall-time over the recorded window, in
        [0, 1]. Tick-to-tick intervals TILE the window, so the only
        non-useful time this meter can subtract is what the loop
        measured: `input_stall()` readings and `overhead()` markers
        (checkpoint saves, eval passes). Unmarked between-tick work is
        counted as step time — wrap it in `overhead()` or the reading
        is an upper bound. NaN before any step records."""
        if (not self._step_hist.count or self._window_start is None
                or self._window_end is None):
            return float("nan")
        wall = self._window_end - self._window_start
        if wall <= 0:
            return float("nan")
        lost = (self._stall_hist.sum if self._stall_hist.count else 0.0) \
            + (self._overhead_hist.sum if self._overhead_hist.count else 0.0)
        useful = max(0.0, self._step_hist.sum - lost)
        return min(1.0, useful / wall)

    def mfu(self) -> float:
        """Model FLOPs utilization in [0,1] against chip peak * num_chips."""
        peak = self.peak_flops if self.peak_flops is not None else peak_flops_per_chip()
        chips = self.num_chips if self.num_chips is not None else jax.device_count()
        if not peak or not self.flops_per_step or not self._step_hist.count:
            return 0.0
        achieved = self.flops_per_step / self.mean_step_time
        return achieved / (peak * chips)

    def summary(self) -> dict[str, float]:
        out = {
            "steps_recorded": float(self.steps_recorded),
            "mean_step_time_s": self.mean_step_time,
            "steps_per_sec": self.steps_per_sec,
        }
        if self._step_hist.count:
            # tail latency, not just means: the sketch keeps p50/p99 at
            # bounded memory for a run of any length
            out["step_time_p50_s"] = self._step_hist.quantile(0.5)
            out["step_time_p99_s"] = self._step_hist.quantile(0.99)
            g = self.goodput
            if g == g:
                out["goodput"] = g
        if self.tokens_per_step:
            out["tokens_per_sec"] = self.tokens_per_sec
            chips = self.num_chips if self.num_chips is not None else jax.device_count()
            out["tokens_per_sec_per_chip"] = self.tokens_per_sec / max(1, chips)
        if self.flops_per_step:
            out["mfu"] = self.mfu()
        if self._dispatch_hist.count:
            out["host_dispatch_us_mean"] = self.host_dispatch_us
        if self._stall_hist.count:
            out["input_stall_us_mean"] = self.input_stall_us
        return out
