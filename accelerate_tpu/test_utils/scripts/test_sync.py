"""Launch-and-assert: gradient-sync / accumulation semantics
(ref test_utils/scripts/test_sync.py, 392 LoC; SURVEY.md §4).

Every rank asserts:
- `accumulate()` flips `sync_gradients` exactly at accumulation boundaries,
  `no_sync()` forces it off, `sync_each_batch` forces it on;
- k accumulated micro-batches produce the same update as one k-times-larger
  batch (the functional analogue of the reference's DDP no_sync grad-equality
  check);
- after a sync step every process holds bitwise-identical parameters;
- the eager chain (compute_gradients -> backward -> clip_grad_norm_ ->
  step) produces the SAME parameters as the fused train_step, on every
  rank — the reference-style migration path is semantically pinned to the
  well-tested fused program.
"""

from __future__ import annotations

import numpy as np


def check_sync_flag_schedule():
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import GradientAccumulationPlugin

    PartialState._reset_state()
    acc = Accelerator(gradient_accumulation_steps=3)
    flags = []
    for _ in range(6):
        with acc.accumulate():
            flags.append(acc.sync_gradients)
    assert flags == [False, False, True, False, False, True], flags

    # no_sync forces accumulation regardless of the schedule
    with acc.no_sync():
        assert not acc.sync_gradients
    # flag restored afterwards (was True at the last boundary)
    assert acc.sync_gradients

    # sync_each_batch syncs on EVERY micro-step (ref dataclasses.py:586)
    PartialState._reset_state()
    acc2 = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=4, sync_each_batch=True
        )
    )
    flags2 = []
    for _ in range(4):
        with acc2.accumulate():
            flags2.append(acc2.sync_gradients)
    assert flags2 == [True] * 4, flags2


def check_accumulation_equivalence():
    """k micro-batches through the accum buffer == one big batch, one step."""
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )

    k, bs = 4, 8
    ds = RegressionDataset(length=k * bs, seed=11)

    def run(steps_cfg):
        PartialState._reset_state()
        acc = Accelerator(gradient_accumulation_steps=steps_cfg)
        ts = TrainState.create(
            apply_fn=None,
            params=regression_params(),
            tx=optax.sgd(0.1),
            use_grad_accum_buffer=steps_cfg > 1,
        )
        step = acc.train_step(regression_loss)
        if steps_cfg > 1:
            for i in range(k):
                sl = slice(i * bs, (i + 1) * bs)
                ts, _ = step(ts, {"x": ds.x[sl], "y": ds.y[sl]})
        else:
            ts, _ = step(ts, {"x": ds.x, "y": ds.y})
        from accelerate_tpu.test_utils import host_values

        return host_values(ts.params)

    accum = run(k)
    big = run(1)
    np.testing.assert_allclose(accum["a"], big["a"], rtol=1e-5)
    np.testing.assert_allclose(accum["b"], big["b"], rtol=1e-5)


def check_params_identical_across_ranks():
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )
    from accelerate_tpu.utils.operations import gather_object

    PartialState._reset_state()
    acc = Accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=32, seed=3)
    loader = acc.prepare(
        [{"x": ds.x[i : i + 4], "y": ds.y[i : i + 4]} for i in range(0, 32, 4)]
    )
    ts = acc.prepare(
        TrainState.create(
            apply_fn=None,
            params=regression_params(),
            tx=optax.sgd(0.05),
            use_grad_accum_buffer=True,
        )
    )
    step = acc.train_step(regression_loss)
    for batch in loader:
        ts, _ = step(ts, batch)
    from accelerate_tpu.test_utils import host_values

    a = float(host_values(ts.params["a"]))
    b = float(host_values(ts.params["b"]))
    everyone = gather_object((a, b))
    assert len(set(everyone)) == 1, f"params diverged across ranks: {everyone}"


def check_eager_chain_matches_fused():
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils import host_values
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )
    from accelerate_tpu.utils.operations import gather_object

    ds = RegressionDataset(length=32, seed=7)
    raw = [{"x": ds.x[i : i + 8], "y": ds.y[i : i + 8]} for i in range(0, 32, 8)]

    # fused reference
    PartialState._reset_state()
    acc = Accelerator(gradient_clipping=0.5)
    loader = acc.prepare(raw)
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.sgd(0.1)))
    step = acc.train_step(regression_loss)
    for batch in loader:
        ts, _ = step(ts, batch)
    fused = {k: float(host_values(v)) for k, v in ts.params.items()}

    # eager chain, same hyperparameters
    PartialState._reset_state()
    acc = Accelerator()
    loader = acc.prepare(raw)
    opt = acc.prepare_optimizer(optax.sgd(0.1), params=acc.prepare(regression_params()))
    for batch in loader:
        with acc.accumulate():
            _, grads = acc.compute_gradients(regression_loss, opt.params, batch)
            acc.backward(grads)
            acc.clip_grad_norm_(max_norm=0.5)
            opt.step()
            opt.zero_grad()
    eager = {k: float(host_values(v)) for k, v in opt.params.items()}

    for k in fused:
        assert abs(fused[k] - eager[k]) < 1e-5, (k, fused[k], eager[k])
    everyone = gather_object(tuple(sorted(eager.items())))
    assert len(set(everyone)) == 1, f"eager params diverged: {everyone}"


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    world = state.num_processes
    check_sync_flag_schedule()
    check_accumulation_equivalence()
    check_params_identical_across_ranks()
    check_eager_chain_matches_fused()
    state = PartialState()
    if state.is_main_process:
        print(f"test_sync: ALL CHECKS PASSED ({world} process(es))")


if __name__ == "__main__":
    main()
