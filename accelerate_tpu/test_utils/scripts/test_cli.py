"""Launch-and-assert: minimal CLI smoke script
(ref test_utils/scripts/test_cli.py — prints the device count so
`accelerate-tpu launch` wiring can be asserted from the outside)."""

from __future__ import annotations


def main() -> None:
    import jax

    from accelerate_tpu.state import PartialState

    state = PartialState()
    print(
        f"Successfully ran on {jax.device_count()} device(s) "
        f"across {state.num_processes} process(es)"
    )


if __name__ == "__main__":
    main()
