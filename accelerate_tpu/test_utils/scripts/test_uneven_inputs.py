"""Launch-and-assert: genuinely uneven inputs across a multi-process world
(ref accelerator.py:1061-1146 `join_uneven_inputs`; round-1 verdict asked
for proof that uneven per-host iteration never hangs or corrupts results).

Every rank asserts: with even_batches=True an indivisible global batch
count still gives every host the SAME number of iterations (collectives
inside the loop would deadlock otherwise — running a gather per step IS
the hang-detector), gather_for_metrics keeps exactly the real samples, and
`join_uneven_inputs(even_batches=True)` rescues an even_batches=False
loader that would otherwise desync the world.
"""

from __future__ import annotations

import numpy as np


def _batches(n_batches: int, rows: int = 8):
    return [
        {"x": (np.arange(rows, dtype=np.float32) + 100 * i).reshape(rows, 1)}
        for i in range(n_batches)
    ]


def check_even_batches_equalizes_iterations(accelerator):
    from accelerate_tpu.utils.operations import gather_object

    world = accelerator.num_processes
    n = 2 * world + 1  # indivisible: one host would get an extra batch raw
    loader = accelerator.prepare(_batches(n))
    steps = 0
    for batch in loader:
        # a collective EVERY step: if any host ran a different loop count
        # this would deadlock (the real failure mode uneven inputs cause);
        # shape is metadata — safe on global arrays spanning both hosts
        counts = gather_object(int(batch["x"].shape[0]))
        assert len(set(counts)) == 1, counts
        steps += 1
    all_steps = gather_object(steps)
    assert len(set(all_steps)) == 1, f"uneven loop counts: {all_steps}"


def check_gather_for_metrics_drops_recycled(accelerator):
    world = accelerator.num_processes
    n = 2 * world + 1
    rows = 8
    loader = accelerator.prepare(_batches(n, rows))
    seen = []
    for batch in loader:
        seen.append(np.asarray(accelerator.gather_for_metrics(batch["x"])))
    got = np.concatenate(seen)
    want_rows = n * rows
    assert got.shape[0] == want_rows, (got.shape, want_rows)
    # every real row exactly once
    want = np.sort(np.concatenate([b["x"] for b in _batches(n, rows)]).ravel())
    np.testing.assert_array_equal(np.sort(got.ravel()), want)


def check_join_uneven_inputs_rescues_uneven_loader(accelerator):
    from accelerate_tpu.utils.operations import gather_object

    world = accelerator.num_processes
    if world == 1:
        return
    from accelerate_tpu.data import prepare_data_loader

    n = 2 * world + 1
    loader = prepare_data_loader(
        _batches(n), even_batches=False, mesh=accelerator.mesh
    )
    accelerator._dataloaders.append(loader)
    # raw uneven loader: per-host lengths genuinely differ
    lens = gather_object(len(list(loader)))
    assert len(set(lens)) > 1, f"expected uneven counts, got {lens}"
    # inside the context the override pads to equal counts; the per-step
    # gather would hang if it didn't
    with accelerator.join_uneven_inputs([None], even_batches=True):
        steps = 0
        for _ in loader:
            gather_object(steps)
            steps += 1
        all_steps = gather_object(steps)
        assert len(set(all_steps)) == 1, all_steps
    # override restored afterwards
    lens2 = gather_object(len(list(loader)))
    assert lens2 == lens, (lens, lens2)


def main():
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    accelerator = Accelerator()
    for check in (
        check_even_batches_equalizes_iterations,
        check_gather_for_metrics_drops_recycled,
        check_join_uneven_inputs_rescues_uneven_loader,
    ):
        accelerator.free_memory()
        check(accelerator)
        PartialState().wait_for_everyone()
    accelerator.print("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
