"""Launch-and-assert: notebook_launcher situational setups
(ref test_utils/scripts/test_notebook.py): the launcher must build the
requested world, and refuse to start when JAX was already initialized in the
calling process (the TPU analogue of the reference's "CUDA already
initialized" guard)."""

from __future__ import annotations

import os


def basic_function():
    from accelerate_tpu.state import PartialState

    print(f"PartialState:\n{PartialState()!r}")


NUM_PROCESSES = int(os.environ.get("ACCELERATE_TPU_NUM_PROCESSES", "1"))


def test_can_initialize():
    from accelerate_tpu.launchers import notebook_launcher

    notebook_launcher(basic_function, (), num_processes=NUM_PROCESSES)


def test_refuses_after_state_initialized():
    """Multi-process launch must fail fast once the runtime is live in this
    process (ref launchers.py:89-97 'CUDA already initialized' guard)."""
    from accelerate_tpu.launchers import notebook_launcher
    from accelerate_tpu.state import AcceleratorState, PartialState

    PartialState()  # initialize the runtime in-process
    assert AcceleratorState._shared_state or PartialState._shared_state
    try:
        notebook_launcher(basic_function, (), num_processes=2)
    except RuntimeError:
        pass
    else:
        raise AssertionError(
            "notebook_launcher(num_processes=2) should refuse to start after "
            "the state singleton is initialized"
        )


def main() -> None:
    print("Test basic notebook can be ran")
    test_can_initialize()
    test_refuses_after_state_initialized()
    print("test_notebook: ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
