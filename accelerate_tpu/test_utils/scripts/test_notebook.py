"""Launch-and-assert: notebook_launcher situational setups
(ref test_utils/scripts/test_notebook.py): the launcher must build the
requested world, and refuse to start when JAX was already initialized in the
calling process (the TPU analogue of the reference's "CUDA already
initialized" guard)."""

from __future__ import annotations

import os


def basic_function():
    from accelerate_tpu.state import PartialState

    print(f"PartialState:\n{PartialState()!r}")


NUM_PROCESSES = int(os.environ.get("ACCELERATE_TPU_NUM_PROCESSES", "1"))


def test_can_initialize():
    from accelerate_tpu.launchers import notebook_launcher

    notebook_launcher(basic_function, (), num_processes=NUM_PROCESSES)


def main() -> None:
    print("Test basic notebook can be ran")
    test_can_initialize()
    print("test_notebook: ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
