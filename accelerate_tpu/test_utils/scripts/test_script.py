"""Bundled launch-and-assert script (ref test_utils/scripts/test_script.py,
804 LoC; SURVEY.md §4).

Run under `accelerate-tpu test` / `accelerate-tpu launch` in ANY world —
single TPU host, N-process localhost CPU world — and every rank asserts:
state init, collective correctness, RNG sync, dataloader sharding
exactly-once coverage, and that a short training run converges identically
on every process.
"""

from __future__ import annotations

import numpy as np


def check_state():
    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes >= 1
    assert 0 <= state.process_index < state.num_processes
    state.wait_for_everyone()
    return state


def check_collectives(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import (
        broadcast_object_list,
        gather,
        gather_object,
        reduce,
    )

    rank = state.process_index
    world = state.num_processes
    # device collective: gather a rank-stamped vector
    local = jnp.full((2,), float(rank))
    gathered = np.asarray(gather(local))
    expect = np.repeat(np.arange(world, dtype=np.float32), 2)
    np.testing.assert_allclose(np.sort(gathered), expect)
    # reduce
    total = float(np.asarray(reduce(jnp.asarray(1.0), reduction="sum")))
    assert total == world, (total, world)
    # host-object collectives (the reference's TPU path lacked gather_object —
    # ref utils/operations.py:462-463; ours must work)
    objs = gather_object({"rank": rank})
    assert sorted(o["rank"] for o in objs) == list(range(world))
    bcast = broadcast_object_list([f"rank-{rank}"])
    assert bcast == ["rank-0"], bcast


def check_rng_sync(state):
    from accelerate_tpu.utils.operations import gather_object
    from accelerate_tpu.utils.random import synchronize_rng_states

    np.random.seed(1234 + state.process_index)  # deliberately diverge
    synchronize_rng_states(["numpy", "python"])  # broadcast rank-0 state
    draw = float(np.random.random())
    draws = gather_object(draw)
    assert len(set(draws)) == 1, f"RNG not synced: {draws}"


def check_dataloader(state):
    from accelerate_tpu.data import prepare_data_loader

    world = state.num_processes
    n, bs = 32, 4
    data = [
        {"idx": np.arange(i, i + bs, dtype=np.int32)}
        for i in range(0, n, bs)
    ]
    loader = prepare_data_loader(data, put_on_device=False)
    seen = []
    for batch in loader:
        seen.append(np.asarray(batch["idx"]))
    local = np.concatenate(seen).ravel()
    from accelerate_tpu.utils.operations import gather_object

    all_seen = np.sort(np.concatenate(gather_object(local)))
    # exactly-once coverage of the dataset across the world (even_batches may
    # duplicate the tail; dedupe before comparing)
    assert set(all_seen.tolist()) == set(range(n)), all_seen


def check_training(state):
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )
    from accelerate_tpu.utils.operations import gather_object

    acc = Accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=64)
    batches = [
        {"x": ds.x[i : i + 8], "y": ds.y[i : i + 8]} for i in range(0, 64, 8)
    ]
    loader = acc.prepare(batches)
    ts = acc.prepare(
        TrainState.create(
            apply_fn=None,
            params=regression_params(),
            tx=optax.sgd(0.1),
            use_grad_accum_buffer=True,
        )
    )
    step = acc.train_step(regression_loss)
    first = last = None
    for _ in range(8):
        for batch in loader:
            ts, metrics = step(ts, batch)
            loss = float(metrics["loss"])
            first = loss if first is None else first
            last = loss
    assert last < first, f"loss did not decrease: {first} -> {last}"
    # every process must hold identical params (grads ride the mesh/world)
    from accelerate_tpu.test_utils import host_values

    a_values = gather_object(float(host_values(ts.params["a"])))
    assert len(set(a_values)) == 1, f"params diverged: {a_values}"
    assert abs(a_values[0] - 2.0) < 0.5, f"did not approach a=2: {a_values[0]}"


def main() -> None:
    state = check_state()
    check_collectives(state)
    check_rng_sync(state)
    check_dataloader(state)
    check_training(state)
    if state.is_main_process:
        print("test_script: ALL CHECKS PASSED "
              f"({state.num_processes} process(es), {state.device_count} device(s))")


if __name__ == "__main__":
    main()
