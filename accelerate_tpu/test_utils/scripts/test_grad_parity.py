"""Launch-and-assert: distributed gradient parity (VERDICT r4 #4).

The data-parallel world must produce EXACTLY the full-batch gradient: one
SGD step on a fixed batch through the sharded `train_step` must land on
the same parameters as a single-device reference computed locally. This
pins the cross-process/cross-device gradient averaging that the multichip
dryrun's `data>1` mesh relies on — in a real launched world, not just the
virtual mesh (runs in the default-CI SMOKE set,
tests/test_launched_scripts.py).
"""

from __future__ import annotations

import numpy as np


def _fixed_batch(cfg, rows: int):
    rng = np.random.default_rng(1234)
    return rng.integers(0, cfg.vocab_size, (rows, 33)).astype(np.int32)


def check_one_step_parity(state):
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.test_utils import host_values

    cfg = llama.LlamaConfig.tiny()
    lr = 0.1

    # ---- distributed: sharded batch, GSPMD-averaged grads, one SGD step
    acc = Accelerator(mixed_precision="no")
    rows = 2 * max(state.num_processes, jax.device_count())
    ids = _fixed_batch(cfg, rows)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=params, tx=optax.sgd(lr))
    )
    loader = acc.prepare([{"input_ids": ids}])
    (batch,) = list(loader)
    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
    ts, metrics = step(ts, batch)
    dist = jax.tree_util.tree_map(
        lambda x: np.asarray(host_values(x)), ts.params
    )

    # ---- reference: same batch, same init, single device, plain jax
    ref_params = llama.init_params(cfg, jax.random.key(0))
    grads = jax.grad(lambda p: llama.causal_lm_loss(
        cfg, p, {"input_ids": ids}))(ref_params)
    ref = jax.tree_util.tree_map(
        lambda p, g: np.asarray(p) - lr * np.asarray(g), ref_params, grads
    )

    flat_d = jax.tree_util.tree_leaves_with_path(dist)
    flat_r = dict(jax.tree_util.tree_leaves_with_path(ref))
    assert flat_d, "no parameters compared"
    for path, d in flat_d:
        r = flat_r[path]
        np.testing.assert_allclose(
            d, r, rtol=1e-4, atol=1e-6,
            err_msg=f"grad parity broken at {jax.tree_util.keystr(path)} "
            f"({state.num_processes} process(es), "
            f"{jax.device_count()} device(s))",
        )
    assert np.isfinite(float(metrics["loss"]))


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_one_step_parity(state)
    if state.is_main_process:
        print(
            f"test_grad_parity: ALL CHECKS PASSED "
            f"({state.num_processes} process(es))"
        )


if __name__ == "__main__":
    main()
