"""Launch-and-assert: elastic restart + checkpoint resume.

Run under `accelerate-tpu launch --num_processes 2 --max_restarts 1`: on
the first attempt every rank trains 5 steps, checkpoints, and then a
non-zero rank hard-crashes (os._exit). The launcher must tear the world
down and relaunch it; the second attempt finds the checkpoint, resumes at
step 5, finishes training, and prints the success marker (torchrun
max_restarts semantics, ref utils/constants.py:46-71).

The state dir comes from ACCELERATE_TPU_TEST_STATE_DIR (the pytest side
creates it); the crash marker file records that attempt 1 already died so
attempt 2 takes the resume path.
"""

from __future__ import annotations

import os

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama

    state_dir = os.environ["ACCELERATE_TPU_TEST_STATE_DIR"]
    marker = os.path.join(state_dir, "crashed_once")
    ckpt_dir = os.path.join(state_dir, "ckpt")

    acc = Accelerator()
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params,
                                       tx=optax.sgd(1e-3)))
    rng = np.random.default_rng(0)
    batch = acc.prepare([{
        "input_ids": rng.integers(0, cfg.vocab_size, (4, 17)).astype(np.int32)
    }])
    (b,) = list(batch)
    step = acc.train_step(lambda p, bb: llama.causal_lm_loss(cfg, p, bb))

    first_attempt = not os.path.exists(marker)
    start = 0
    if not first_attempt:
        result = acc.load_state(ckpt_dir, state=ts)
        ts = result["train_states"][0]
        start = int(ts.step)
        assert start == 5, f"expected resume at step 5, got {start}"

    for i in range(start, 10):
        ts, m = step(ts, b)
        if first_attempt and i == 4:
            acc.save_state(ckpt_dir, state=ts)
            acc.wait_for_everyone()
            if acc.is_main_process:
                with open(marker, "w") as f:
                    f.write("1")
            acc.wait_for_everyone()
        if first_attempt and i == 5 and not acc.is_main_process:
            os._exit(17)  # hard crash: no cleanup, no exception path

    assert int(ts.step) == 10, int(ts.step)
    assert np.isfinite(float(m["loss"]))
    acc.print("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
