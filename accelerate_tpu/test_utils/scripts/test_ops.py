"""Launch-and-assert: pytree collectives
(ref test_utils/scripts/test_ops.py, 179 LoC; SURVEY.md §4).

Every rank asserts gather/reduce/broadcast/pad_across_processes on nested
pytrees, rank-uneven shapes, and host-object collectives.
"""

from __future__ import annotations

import numpy as np


def check_gather_pytree(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import gather

    rank, world = state.process_index, state.num_processes
    tree = {
        "a": jnp.full((2, 3), float(rank)),
        "nested": [jnp.arange(4, dtype=jnp.float32) + rank],
    }
    out = gather(tree)
    a = np.asarray(out["a"])
    assert a.shape == (2 * world, 3), a.shape
    assert set(np.unique(a).tolist()) == set(float(r) for r in range(world))
    n = np.asarray(out["nested"][0])
    assert n.shape == (4 * world,), n.shape


def check_reduce(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import reduce

    rank, world = state.process_index, state.num_processes
    tree = {"x": jnp.asarray([float(rank + 1)])}
    total = np.asarray(reduce(tree, reduction="sum")["x"])
    np.testing.assert_allclose(total, [world * (world + 1) / 2])
    mean = np.asarray(reduce(tree, reduction="mean")["x"])
    np.testing.assert_allclose(mean, [(world + 1) / 2])


def check_broadcast(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import broadcast, broadcast_object_list

    rank = state.process_index
    tree = {"w": jnp.full((3,), float(rank)), "b": jnp.asarray([float(rank) * 2])}
    out = broadcast(tree, from_process=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.zeros(3))
    np.testing.assert_allclose(np.asarray(out["b"]), [0.0])

    objs = broadcast_object_list([{"rank": rank}, rank * 10])
    assert objs == [{"rank": 0}, 0], objs


def check_pad_across_processes(state):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import gather, pad_across_processes

    rank, world = state.process_index, state.num_processes
    # rank-dependent length: rank r holds r+1 rows
    local = jnp.full((rank + 1, 2), float(rank))
    padded = pad_across_processes(local, dim=0, pad_index=-1.0)
    assert padded.shape[0] == world, padded.shape
    gathered = np.asarray(gather(padded))
    assert gathered.shape == (world * world, 2), gathered.shape
    # each rank's block: r+1 real rows then pads
    blocks = gathered.reshape(world, world, 2)
    for r in range(world):
        np.testing.assert_allclose(blocks[r, : r + 1], float(r))
        if r + 1 < world:
            np.testing.assert_allclose(blocks[r, r + 1 :], -1.0)

    # pad_first puts padding before the data
    padded_first = np.asarray(
        pad_across_processes(local, dim=0, pad_index=-1.0, pad_first=True)
    )
    np.testing.assert_allclose(padded_first[: world - (rank + 1)], -1.0)
    np.testing.assert_allclose(padded_first[world - (rank + 1) :], float(rank))


def check_gather_object(state):
    from accelerate_tpu.utils.operations import gather_object

    rank, world = state.process_index, state.num_processes
    # arbitrary (non-tensor) payloads — the reference's TPU path raised
    # NotImplementedError here (ref utils/operations.py:462-463); ours works
    objs = gather_object({"rank": rank, "msg": f"hello-{rank}"})
    assert len(objs) == world
    assert sorted(o["rank"] for o in objs) == list(range(world))


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_gather_pytree(state)
    check_reduce(state)
    check_broadcast(state)
    check_pad_across_processes(state)
    check_gather_object(state)
    if state.is_main_process:
        print(f"test_ops: ALL CHECKS PASSED ({state.num_processes} process(es))")


if __name__ == "__main__":
    main()
