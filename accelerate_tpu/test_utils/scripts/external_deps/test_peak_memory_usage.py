"""Launch-and-assert: peak-memory regression gate
(ref test_utils/scripts/external_deps/test_peak_memory_usage.py:226-229 —
asserts peak memory stays under an upper bound; TorchTracemalloc :39-80).

Every rank trains a tiny model and asserts the device-memory footprint —
live `jax.Array` bytes (exact on every backend) plus allocator peak stats
where the backend reports them — stays under a fixed budget, and that
`free_memory` actually releases the arrays it is handed.
"""

from __future__ import annotations


def _run_tiny_training():
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )

    PartialState._reset_state()
    acc = Accelerator()
    ds = RegressionDataset(length=64, seed=2)
    loader = acc.prepare(
        [{"x": ds.x[i : i + 8], "y": ds.y[i : i + 8]} for i in range(0, 64, 8)]
    )
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=regression_params(), tx=optax.sgd(0.1))
    )
    step = acc.train_step(regression_loss)
    for batch in loader:
        ts, _ = step(ts, batch)
    return acc, ts


def check_peak_memory_bound():
    from accelerate_tpu.profiler import device_memory_stats, live_array_bytes

    acc, ts = _run_tiny_training()
    live = live_array_bytes()
    # regression params + adam-free sgd state + a handful of batches: a few
    # KB of payload. 64 MB is the generous ceiling that still catches a leak
    # of retained per-step arrays (the failure mode this gate exists for).
    budget = 64 * 1024 * 1024
    assert live < budget, f"live array bytes {live} exceed budget {budget}"
    stats = device_memory_stats()
    peak = stats.get("peak_bytes_in_use", 0)
    if peak:  # backends without allocator stats report {}
        assert peak < 4 * budget, f"allocator peak {peak} exceeds bound"


def check_free_memory_releases():
    import numpy as np
    import jax

    from accelerate_tpu.profiler import live_array_bytes

    base = live_array_bytes()
    big = jax.device_put(np.zeros((1024, 1024), np.float32))  # 4 MB
    big.block_until_ready()
    held = live_array_bytes()
    assert held >= base + 4 * 1024 * 1024 - 4096
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator()
    (big,) = acc.free_memory(big)
    assert big is None
    after = live_array_bytes()
    assert after < held, (base, held, after)


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_peak_memory_bound()
    check_free_memory_releases()
    state = PartialState()
    if state.is_main_process:
        print(
            f"test_peak_memory_usage: ALL CHECKS PASSED ({state.num_processes} process(es))"
        )


if __name__ == "__main__":
    main()
