"""Launch-and-assert: checkpoint save/resume equivalence
(ref test_utils/scripts/external_deps/test_checkpointing.py; SURVEY.md §3.6).

Every rank asserts:
- train k steps, `save_state`, train k more → params P_direct;
- fresh run, `load_state`, train the same k more → params P_resumed == P_direct
  bitwise (optimizer moments, scheduler step and RNG all round-trip);
- mid-epoch resume via `skip_first_batches` replays exactly the un-seen tail.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


def _make_world(tmpdir: str, total_limit: int | None = None):
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )
    from accelerate_tpu.utils import ProjectConfiguration

    PartialState._reset_state()
    acc = Accelerator(
        project_dir=tmpdir,
        project_config=ProjectConfiguration(
            project_dir=tmpdir,
            automatic_checkpoint_naming=True,
            total_limit=total_limit,
        ),
    )
    # scale with the world so every host still sees 8 batches per epoch
    # (prepared loaders stride whole batches across hosts)
    n = 64 * acc.num_processes
    ds = RegressionDataset(length=n, seed=7)
    batches = [
        {"x": ds.x[i : i + 8], "y": ds.y[i : i + 8]} for i in range(0, n, 8)
    ]
    loader = acc.prepare(batches)
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=regression_params(), tx=optax.adam(0.05))
    )
    step = acc.train_step(regression_loss)
    return acc, loader, ts, step


def check_save_resume_equivalence(tmpdir: str):
    import jax

    acc, loader, ts, step = _make_world(tmpdir)
    it = iter(loader)
    for _ in range(4):
        ts, _ = step(ts, next(it))
    ckpt = acc.save_state(state=ts)
    assert os.path.isdir(ckpt), ckpt
    for _ in range(4):
        ts, _ = step(ts, next(it))
    from accelerate_tpu.test_utils import host_values

    direct = host_values(ts.params)

    # fresh world resumes from the checkpoint and replays the same tail
    acc2, loader2, ts2, step2 = _make_world(tmpdir)
    restored = acc2.load_state(ckpt, state=ts2)
    ts2 = restored.get("train_states", [ts2])[0]
    it2 = iter(loader2)
    for _ in range(4):  # skip the batches the first run consumed pre-save
        next(it2)
    for _ in range(4):
        ts2, _ = step2(ts2, next(it2))
    resumed = host_values(ts2.params)
    np.testing.assert_array_equal(direct["a"], resumed["a"])
    np.testing.assert_array_equal(direct["b"], resumed["b"])


def check_skip_first_batches(tmpdir: str):
    from accelerate_tpu.test_utils import host_values

    acc, loader, _, _ = _make_world(tmpdir)
    all_batches = [host_values(b["x"]) for b in loader]
    tail = [host_values(b["x"]) for b in acc.skip_first_batches(loader, 3)]
    assert len(tail) == len(all_batches) - 3
    for got, want in zip(tail, all_batches[3:]):
        np.testing.assert_array_equal(got, want)


def check_total_limit(tmpdir: str):
    from accelerate_tpu.utils.constants import CHECKPOINT_DIR_PREFIX

    acc, loader, ts, step = _make_world(tmpdir, total_limit=2)
    it = iter(loader)
    for _ in range(3):
        ts, _ = step(ts, next(it))
        acc.save_state(state=ts)
    base = os.path.join(tmpdir, "checkpoints")
    kept = sorted(d for d in os.listdir(base) if d.startswith(CHECKPOINT_DIR_PREFIX))
    assert len(kept) == 2, kept  # oldest pruned (ref ProjectConfiguration.total_limit)


def main() -> None:
    import shutil

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.operations import broadcast_object_list

    state = PartialState()
    # multi-host checkpointing needs ONE directory every process agrees on
    # (orbax: non-primary hosts wait for the primary's commit markers) — the
    # main process creates it and broadcasts the path, exactly as a real
    # multi-host run points every host at the same shared-filesystem dir
    dirs = (
        [tempfile.mkdtemp() for _ in range(3)]
        if state.is_main_process else [None, None, None]
    )
    dirs = broadcast_object_list(dirs)
    tmp_a, tmp_b, tmp_c = dirs
    check_save_resume_equivalence(tmp_a)
    check_skip_first_batches(tmp_b)
    check_total_limit(tmp_c)
    # cleanup on the success path only: a barrier in a finally would hang the
    # world when one host fails mid-check (its peers are still inside other
    # collectives); a failed run leaking a tmpdir is the lesser evil
    state.wait_for_everyone()
    if state.is_main_process:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
        print(f"test_checkpointing: ALL CHECKS PASSED ({state.num_processes} process(es))")


if __name__ == "__main__":
    main()
