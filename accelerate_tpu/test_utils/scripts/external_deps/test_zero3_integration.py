"""Launch-and-assert: full-parameter sharding (ZeRO-3 / FSDP analogue)
(ref test_utils/scripts/external_deps/test_zero3_integration.py; SURVEY §2.2 —
ZeRO-3 ≙ params on the `fsdp` mesh axis under GSPMD).

Every rank asserts:
- preparing a TrainState under `FullyShardedDataParallelPlugin(FULL_SHARD)`
  actually shards large params over the `fsdp` axis (per-device bytes drop);
- a sharded train step produces the SAME parameters as the unsharded
  data-parallel run on identical data — numerics are sharding-invariant;
- `get_state_dict` regathers full (unsharded) host arrays for export.
"""

from __future__ import annotations

import numpy as np


def _mlp_params(key, width: int = 256, depth: int = 3):
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(key, depth)
    return {
        f"layer_{i}": {
            "kernel": jax.random.normal(keys[i], (width, width)) * 0.05,
            "bias": jnp.zeros((width,)),
        }
        for i in range(depth)
    }


def _mlp_loss(params, batch):
    import jax

    x = batch["x"]
    for i in range(len(params)):
        layer = params[f"layer_{i}"]
        x = jax.nn.tanh(x @ layer["kernel"] + layer["bias"])
    return ((x - batch["y"]) ** 2).mean()


def _train(plugin, batches, steps: int):
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(fsdp_plugin=plugin, gradient_clipping=1.0)
    # each host strides every num_processes-th batch: replicate the batch
    # list so `steps` next() calls never exhaust a host's shard
    batches = list(batches) * acc.num_processes
    params = _mlp_params(jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adam(1e-2)))
    step = acc.train_step(_mlp_loss)
    loader = acc.prepare(batches)
    it = iter(loader)
    for _ in range(steps):
        ts, _ = step(ts, next(it))
    return acc, ts


def check_params_are_sharded():
    import jax

    from accelerate_tpu.utils import FullyShardedDataParallelPlugin
    from accelerate_tpu.utils.constants import AXIS_FSDP

    rng = np.random.default_rng(0)
    batches = [
        {"x": rng.normal(size=(8, 256)).astype(np.float32),
         "y": rng.normal(size=(8, 256)).astype(np.float32)}
        for _ in range(4)
    ]
    acc, ts = _train(FullyShardedDataParallelPlugin(), batches, steps=2)
    n_shards = acc.mesh.shape.get(AXIS_FSDP, 1)
    if n_shards > 1:
        kernel = ts.params["layer_0"]["kernel"]
        spec = kernel.sharding.spec
        assert AXIS_FSDP in jax.tree_util.tree_leaves(tuple(spec)), (
            f"FULL_SHARD left layer_0/kernel replicated: {spec}"
        )
        shard_elems = int(np.prod(kernel.addressable_shards[0].data.shape))
        assert shard_elems == int(np.prod(kernel.shape)) // n_shards, (
            shard_elems, kernel.shape, n_shards
        )


def check_sharded_matches_replicated():
    import jax

    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    rng = np.random.default_rng(1)
    batches = [
        {"x": rng.normal(size=(8, 256)).astype(np.float32),
         "y": rng.normal(size=(8, 256)).astype(np.float32)}
        for _ in range(6)
    ]
    acc_full, ts_full = _train(FullyShardedDataParallelPlugin("FULL_SHARD"), batches, 6)
    # get_state_dict regathers multi-host shards (device_get cannot read an
    # array spanning non-addressable devices)
    full = acc_full.get_state_dict(ts_full)["layer_2"]["kernel"]
    acc_none, ts_none = _train(FullyShardedDataParallelPlugin("NO_SHARD"), batches, 6)
    none = acc_none.get_state_dict(ts_none)["layer_2"]["kernel"]
    # sharded vs replicated matmuls reduce in different orders (more so
    # across hosts); after 6 adam steps a small drift is expected (ref test
    # asserts metric parity, not bitwise equality)
    np.testing.assert_allclose(full, none, rtol=3e-3, atol=5e-5)


def check_state_dict_regathers():
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    rng = np.random.default_rng(2)
    batches = [
        {"x": rng.normal(size=(8, 256)).astype(np.float32),
         "y": rng.normal(size=(8, 256)).astype(np.float32)}
    ]
    acc, ts = _train(FullyShardedDataParallelPlugin(), batches, 1)
    sd = acc.get_state_dict(ts)
    kernel = sd["layer_0"]["kernel"]
    assert isinstance(kernel, np.ndarray) and kernel.shape == (256, 256)


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_params_are_sharded()
    check_sharded_matches_replicated()
    check_state_dict_regathers()
    state = PartialState()
    if state.is_main_process:
        print(
            f"test_zero3_integration: ALL CHECKS PASSED ({state.num_processes} process(es))"
        )


if __name__ == "__main__":
    main()
