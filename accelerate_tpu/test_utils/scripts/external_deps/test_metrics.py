"""Launch-and-assert: `gather_for_metrics` exact-sample-count semantics
(ref test_utils/scripts/external_deps/test_metrics.py, 306 LoC; SURVEY §5).

Every rank asserts, for dataset lengths that do and don't divide the world
size, that gathering per-batch predictions over a prepared dataloader yields
each sample EXACTLY once — no duplicated wraparound tail — and in dataset
order; and that `gather_object` path behaves the same for non-array payloads.
"""

from __future__ import annotations

import numpy as np


def _world():
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    return Accelerator()


def check_exact_sample_count(length: int, batch_size: int):
    """Identity 'model': gather_for_metrics over all batches must reproduce
    arange(length) exactly (ref test_metrics.py semantics)."""
    acc = _world()
    data = np.arange(length, dtype=np.float32)
    batches = [
        {"x": data[i : i + batch_size]} for i in range(0, length, batch_size)
    ]
    loader = acc.prepare(batches)
    seen = []
    for batch in loader:
        out = acc.gather_for_metrics(batch["x"])
        seen.append(np.asarray(out).reshape(-1))
    got = np.concatenate(seen)
    assert got.shape[0] == length, (
        f"length={length} bs={batch_size}: gathered {got.shape[0]} samples"
    )
    np.testing.assert_array_equal(np.sort(got), data)


def check_object_gather_path():
    acc = _world()
    payload = {"rank": acc.process_index, "tag": "metrics"}
    everyone = acc.gather_for_metrics([payload], use_gather_object=True)
    assert len(everyone) == acc.num_processes
    assert sorted(d["rank"] for d in everyone) == list(range(acc.num_processes))


def check_pytree_gather():
    """gather_for_metrics recurses over dict batches (ref :2331)."""
    acc = _world()
    n = 24
    batches = [
        {"logits": np.full((8, 2), i, np.float32), "labels": np.full((8,), i, np.int32)}
        for i in range(n // 8)
    ]
    loader = acc.prepare(batches)
    logits, labels = [], []
    for batch in loader:
        g = acc.gather_for_metrics(batch)
        logits.append(np.asarray(g["logits"]))
        labels.append(np.asarray(g["labels"]))
    assert sum(x.shape[0] for x in logits) == n
    assert sum(x.shape[0] for x in labels) == n
    for lg, lb in zip(logits, labels):
        np.testing.assert_array_equal(lg[:, 0].astype(np.int32), lb)


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    # lengths chosen to hit: exact division, ragged tail smaller than one
    # batch, ragged tail spanning hosts (ref test_metrics 99-sample case)
    for length, bs in [(64, 8), (60, 8), (99, 8), (16, 16)]:
        check_exact_sample_count(length, bs)
    check_object_gather_path()
    check_pytree_gather()
    state = PartialState()
    if state.is_main_process:
        print(f"test_metrics: ALL CHECKS PASSED ({state.num_processes} process(es))")


if __name__ == "__main__":
    main()
