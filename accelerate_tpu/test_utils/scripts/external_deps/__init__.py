"""Heavier launch-and-assert scripts (ref test_utils/scripts/external_deps/):
checkpoint round-trips, metric-gather exactness, training-quality and
peak-memory regression gates, pipeline inference, full-shard (ZeRO-3
analogue) integration. Each script's `main()` asserts on every rank and
prints "ALL CHECKS PASSED" from the main process.
"""
