"""Launch-and-assert: pipeline-parallel inference
(ref test_utils/scripts/external_deps/test_pippy.py — PiPPy tracing/stage
scheduling; here GPipe micro-batching over the mesh `stage` axis).

Every rank asserts:
- `prepare_pipeline` over a stage axis reproduces the sequential forward
  bitwise-close for several chunk counts;
- every process receives the full output (the reference's
  `gather_output=True` contract);
- `prepare_sharded_inference` (the GSPMD serving path) agrees too.
"""

from __future__ import annotations

import numpy as np


def _layer_fn(layer, x):
    import jax

    return x + jax.nn.tanh(x @ layer["kernel"] + layer["bias"])


def _stacked_layers(key, n_layers: int, width: int):
    import jax
    import jax.numpy as jnp

    return {
        "kernel": jax.random.normal(key, (n_layers, width, width)) * 0.05,
        "bias": jnp.zeros((n_layers, width)),
    }


def _sequential_reference(layers, x):
    import jax

    def body(h, layer):
        return _layer_fn(layer, h), None

    out, _ = jax.lax.scan(body, x, layers)
    return out


def check_pipeline_matches_sequential():
    import jax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import MeshConfig
    from accelerate_tpu.utils.constants import AXIS_DATA, AXIS_STAGE

    n_devices = len(jax.devices())
    if n_devices < 2:
        return  # single-chip world: stage axis impossible; covered elsewhere
    stages = 2 if n_devices % 2 == 0 else 1
    if stages < 2:
        return

    PartialState._reset_state()
    acc = Accelerator(
        mesh_config=MeshConfig(axes={AXIS_DATA: n_devices // stages,
                                     AXIS_STAGE: stages})
    )
    from accelerate_tpu.inference import prepare_pipeline

    width, n_layers, batch = 64, 8, 16
    layers = _stacked_layers(jax.random.key(0), n_layers, width)
    x = np.asarray(
        jax.random.normal(jax.random.key(1), (batch, width)), dtype=np.float32
    )
    want = np.asarray(_sequential_reference(layers, x))

    for num_chunks in (2, 4):
        model = prepare_pipeline(
            _layer_fn, layers, num_chunks=num_chunks, mesh=acc.mesh
        )
        got = np.asarray(model(x))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def check_gspmd_serving_path():
    import jax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.inference import prepare_sharded_inference
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator()
    width, n_layers = 64, 4
    layers = _stacked_layers(jax.random.key(2), n_layers, width)

    def forward(params, x):
        return _sequential_reference(params, x)

    served_fn, sharded_params = prepare_sharded_inference(
        forward, layers, mesh=acc.mesh
    )
    x = np.asarray(
        jax.random.normal(jax.random.key(3), (16, width)), dtype=np.float32
    )
    got = np.asarray(served_fn(sharded_params, x))
    want = np.asarray(forward(layers, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_pipeline_matches_sequential()
    check_gspmd_serving_path()
    state = PartialState()
    if state.is_main_process:
        print(
            f"test_pipeline_inference: ALL CHECKS PASSED ({state.num_processes} process(es))"
        )


if __name__ == "__main__":
    main()
