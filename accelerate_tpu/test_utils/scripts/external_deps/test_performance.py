"""Launch-and-assert: distributed training quality gate
(ref test_utils/scripts/external_deps/test_performance.py:195-203 — asserts
distributed metric >= single-process baseline minus a threshold).

Every rank trains (a) the regression workload and (b) a tiny BERT classifier
on a deterministic synthetic task, then asserts convergence quality beats a
fixed baseline threshold — the functional analogue of the reference's
accuracy/F1-vs-baseline regression gate.
"""

from __future__ import annotations

import numpy as np


def check_regression_convergence():
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_loss,
        regression_params,
    )

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="no", gradient_clipping=1.0)
    # world-scaled so every host runs the same number of steps per epoch
    n = 96 * acc.num_processes
    ds = RegressionDataset(length=n, seed=1)
    batches = [{"x": ds.x[i : i + 8], "y": ds.y[i : i + 8]} for i in range(0, n, 8)]
    loader = acc.prepare(batches)
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=regression_params(), tx=optax.adam(0.1))
    )
    step = acc.train_step(regression_loss)
    for _ in range(12):  # epochs
        for batch in loader:
            ts, _ = step(ts, batch)
    from accelerate_tpu.test_utils import host_values

    a = float(host_values(ts.params["a"]))
    b = float(host_values(ts.params["b"]))
    # ground truth y = 2x + 1 (+0.1 noise): the quality gate
    assert abs(a - 2.0) < 0.15, f"slope {a} off baseline 2.0"
    assert abs(b - 1.0) < 0.15, f"intercept {b} off baseline 1.0"


def _synthetic_cls_batches(vocab: int, seq: int, n: int, bs: int, seed: int):
    """Token-counting task: label = (count of token 1 in the sequence) % 2 —
    learnable by a 2-layer transformer, deterministic across ranks."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(n, seq)).astype(np.int32)
    labels = (np.sum(ids == 1, axis=1) % 2).astype(np.int32)
    return [
        {"input_ids": ids[i : i + bs], "labels": labels[i : i + bs]}
        for i in range(0, n, bs)
    ]


def check_bert_classifier_learns():
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import bert
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="no", gradient_clipping=1.0)
    cfg = bert.BertConfig.tiny(
        vocab_size=32, max_position_embeddings=16, num_labels=2
    )
    params = bert.init_params(cfg, jax.random.key(0))

    def loss_fn(p, batch):
        logits = bert.forward(cfg, p, batch["input_ids"])
        labels = batch["labels"]
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()
        acc_metric = (jnp.argmax(logits, -1) == labels).mean()
        return loss, {"accuracy": acc_metric}

    batches = _synthetic_cls_batches(vocab=32, seq=16,
                                     n=256 * acc.num_processes, bs=16, seed=5)
    loader = acc.prepare(batches)
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=params, tx=optax.adamw(3e-3))
    )
    step = acc.train_step(loss_fn, has_aux=True)
    first_loss = last_metrics = None
    for epoch in range(6):
        for batch in loader:
            ts, metrics = step(ts, batch)
            if first_loss is None:
                first_loss = float(metrics["loss"])
            last_metrics = (float(metrics["loss"]), float(metrics["aux"]["accuracy"]))
    final_loss, final_acc = last_metrics
    # the regression gate: training must actually learn the task
    assert final_loss < first_loss * 0.8, (first_loss, final_loss)
    assert final_acc > 0.65, f"final train accuracy {final_acc} below baseline gate"


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    check_regression_convergence()
    check_bert_classifier_learns()
    state = PartialState()
    if state.is_main_process:
        print(f"test_performance: ALL CHECKS PASSED ({state.num_processes} process(es))")


if __name__ == "__main__":
    main()
