"""Launch-and-assert: data-loader sharding semantics
(ref test_utils/scripts/test_distributed_data_loop.py, 312 LoC; SURVEY.md §4).

Every rank asserts: BatchSamplerShard stride/split coverage, even_batches
wraparound vs uneven tails, skip_first_batches resume, dispatcher-vs-shard
equivalence, and gather_for_metrics exact-sample-count semantics.
"""

from __future__ import annotations

import numpy as np


class _Batches:
    """Plain batch-index sampler: `n` samples in batches of `bs`."""

    def __init__(self, n, bs, drop_last=False):
        self.n, self.batch_size, self.drop_last = n, bs, drop_last

    def __len__(self):
        q, r = divmod(self.n, self.batch_size)
        return q if (self.drop_last or r == 0) else q + 1

    def __iter__(self):
        batch = []
        for i in range(self.n):
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch


def check_sampler_shard_coverage(state):
    from accelerate_tpu.data import BatchSamplerShard

    world, rank = state.num_processes, state.process_index
    # evenly divisible case: exact partition, no duplicates anywhere
    shard = BatchSamplerShard(_Batches(8 * world, 4), world, rank)
    mine = [i for b in shard for i in b]
    from accelerate_tpu.utils.operations import gather_object

    everyone = sorted(i for sub in gather_object(mine) for i in sub)
    assert everyone == list(range(8 * world)), everyone


def check_even_batches_wraparound(state):
    from accelerate_tpu.data import BatchSamplerShard

    world, rank = state.num_processes, state.process_index
    if world == 1:
        # single process: nothing to even out — the tail batch stays short
        # and the dataset is covered exactly once (ref data_loader.py:158-206)
        for even in (True, False):
            shard = BatchSamplerShard(_Batches(10, 4), 1, 0, even_batches=even)
            batches = list(shard)
            assert [len(b) for b in batches] == [4, 4, 2], batches
            assert [i for b in batches for i in b] == list(range(10))
        return
    # multi-process: every rank must yield the SAME number of batches, all
    # full-size, covering the dataset (dupes allowed only from wraparound)
    n = 4 * world + 2  # uneven tail
    shard = BatchSamplerShard(_Batches(n, 2), world, rank, even_batches=True)
    mine = list(shard)
    assert all(len(b) == 2 for b in mine), mine
    from accelerate_tpu.utils.operations import gather_object

    counts = gather_object(len(mine))
    assert len(set(counts)) == 1, f"ranks yielded different batch counts: {counts}"
    flat = [i for sub in gather_object([i for b in mine for i in b]) for i in sub]
    assert set(flat) == set(range(n)), (sorted(set(flat)), n)


def check_skip_first_batches(state):
    from accelerate_tpu.data import prepare_data_loader, skip_first_batches

    data = [{"v": np.full((2,), i, dtype=np.int32)} for i in range(6)]
    loader = prepare_data_loader(data, put_on_device=False)
    full = [int(np.asarray(b["v"])[0]) for b in loader]
    resumed = skip_first_batches(loader, 2)
    rest = [int(np.asarray(b["v"])[0]) for b in resumed]
    assert rest == full[2:], (full, rest)
    # the original loader is untouched
    again = [int(np.asarray(b["v"])[0]) for b in loader]
    assert again == full


def check_dispatcher_matches_shard(state):
    """Dispatcher (rank0 fetches + broadcasts) must deliver the same global
    sample set as per-rank sharding (ref data_loader.py:562-737)."""
    from accelerate_tpu.data import prepare_data_loader
    from accelerate_tpu.utils.operations import gather_object

    world = state.num_processes
    n, bs = 8 * world, world  # dispatcher splits each global batch across ranks
    data = [
        {"idx": np.arange(i, i + bs, dtype=np.int32)} for i in range(0, n, bs)
    ]
    shard_loader = prepare_data_loader(data, put_on_device=False)
    shard_seen = np.sort(
        np.concatenate(
            [np.asarray(b["idx"]).ravel() for b in shard_loader]
        )
    )
    disp_loader = prepare_data_loader(data, put_on_device=False, dispatch_batches=True)
    disp_seen = np.sort(
        np.concatenate([np.asarray(b["idx"]).ravel() for b in disp_loader])
    )
    all_shard = np.sort(np.concatenate(gather_object(shard_seen)))
    all_disp = np.sort(np.concatenate(gather_object(disp_seen)))
    np.testing.assert_array_equal(all_shard, np.arange(n))
    np.testing.assert_array_equal(np.unique(all_disp), np.arange(n))


def prepare_dispatch(acc, data):
    from accelerate_tpu.data import prepare_data_loader

    loader = prepare_data_loader(data, put_on_device=False, dispatch_batches=True)
    acc._dataloaders.append(loader)
    return loader


def check_gather_for_metrics_exact_count(state):
    """Uneven final batch: gather_for_metrics drops pad duplicates so eval
    sees each sample exactly once (ref accelerator.py:2331-2403)."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator()
    world = acc.num_processes
    # 2*world+1 with bs=2 leaves a short tail batch at EVERY world size
    # (world=1 in-process included), so the pad-dedup path always runs
    n, bs = 2 * world + 1, 2
    data = [
        {"idx": np.arange(i, min(i + bs, n), dtype=np.int32)}
        for i in range(0, n, bs)
    ]
    # multi-host: the dispatcher pads the short GLOBAL tail batch and records
    # the real count; stride-sharding would leave asymmetric local tails
    loader = acc.prepare_data_loader(
        data, device_placement=False
    ) if world == 1 else prepare_dispatch(acc, data)
    seen = []
    for batch in loader:
        out = acc.gather_for_metrics(batch)
        if acc.is_main_process:
            seen.append(np.asarray(out["idx"]).ravel())
    if acc.is_main_process:
        got = np.sort(np.concatenate(seen))
        np.testing.assert_array_equal(got, np.arange(n))


def main() -> None:
    from accelerate_tpu.state import PartialState

    state = PartialState()
    world = state.num_processes
    check_sampler_shard_coverage(state)
    check_even_batches_wraparound(state)
    check_skip_first_batches(state)
    check_dispatcher_matches_shard(state)
    check_gather_for_metrics_exact_count(state)
    state = PartialState()
    if state.is_main_process:
        print(f"test_distributed_data_loop: ALL CHECKS PASSED ({world} process(es))")


if __name__ == "__main__":
    main()
