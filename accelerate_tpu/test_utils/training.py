"""Tiny deterministic workloads (ref test_utils/training.py:1-101).

`RegressionDataset` draws y = 2x + 1 (+noise); `regression_params`/
`regression_forward` are the functional JAX stand-ins for the reference's
`RegressionModel` nn.Module — one weight, one bias, so convergence and
cross-process parity are exact and fast to assert.
"""

from __future__ import annotations

import numpy as np


class RegressionDataset:
    def __init__(self, a: float = 2.0, b: float = 1.0, length: int = 64,
                 seed: int = 42) -> None:
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + rng.normal(scale=0.1, size=(length,))).astype(
            np.float32
        )

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, i: int) -> dict:
        return {"x": self.x[i], "y": self.y[i]}


def regression_params(a: float = 0.0, b: float = 0.0) -> dict:
    import jax.numpy as jnp

    return {"a": jnp.asarray(a, jnp.float32), "b": jnp.asarray(b, jnp.float32)}


def regression_forward(params: dict, x):
    return params["a"] * x + params["b"]


def regression_loss(params: dict, batch: dict):
    pred = regression_forward(params, batch["x"])
    return ((pred - batch["y"]) ** 2).mean()
