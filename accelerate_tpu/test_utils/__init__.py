"""Shipped test harness (ref src/accelerate/test_utils/, 3994 LoC).

Shipped inside the package so `accelerate-tpu test` works from any install
(ref commands/test.py runs the bundled test_script). Capability gating skips
by hardware, never mocks (ref testing.py:122-392).
"""

from __future__ import annotations

import functools
import os
import unittest

import numpy as np


def device_platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError:
        return "none"


def require_tpu(test_case):
    """Skip unless a real TPU backend is attached (ref testing.py:216)."""
    return unittest.skipUnless(device_platform() == "tpu", "test requires TPU")(
        test_case
    )


def require_multi_device(test_case):
    """Skip unless >1 device is visible (real or virtual)
    (ref testing.py require_multi_device)."""
    import jax

    return unittest.skipUnless(
        jax.device_count() > 1, "test requires multiple devices"
    )(test_case)


def require_multi_process(test_case):
    import jax

    return unittest.skipUnless(
        jax.process_count() > 1, "test requires a multi-process world"
    )(test_case)


@functools.lru_cache()
def multiprocess_backend_supported() -> bool:
    """Whether this jaxlib can run MULTI-PROCESS computations on the CPU
    backend: some builds raise INVALID_ARGUMENT ("Multiprocess computations
    aren't implemented on the CPU backend") the moment a 2-process world
    compiles anything global, which no launched-script test can survive.
    Probed once per session with a minimal 2-rank world (rendezvous + one
    process_allgather) so the whole launch matrix can skip with a reason
    instead of burning its timeout per parametrization."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = (
        "import sys, jax, numpy as np\n"
        f"jax.distributed.initialize(coordinator_address='127.0.0.1:{port}',"
        " num_processes=2, process_id=int(sys.argv[1]))\n"
        "from jax.experimental import multihost_utils\n"
        "multihost_utils.process_allgather(np.zeros(1))\n"
        "print('MP_OK')\n"
    )
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = [
        subprocess.Popen([sys.executable, "-c", code, str(rank)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env, start_new_session=True)
        for rank in (0, 1)
    ]
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
            ok = ok and p.returncode == 0 and "MP_OK" in out
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            p.communicate()
            ok = False
    return ok


def slow(test_case):
    """Gate by RUN_SLOW=1 (ref testing.py slow decorator)."""
    from ..utils.environment import parse_flag_from_env

    return unittest.skipUnless(parse_flag_from_env("RUN_SLOW"), "slow test")(
        test_case
    )


def are_the_same_tensors(tensor) -> bool:
    """True iff every process holds an identical copy
    (ref testing.py:474-483)."""
    from ..utils.operations import gather

    stacked = np.asarray(gather(tensor[None]))
    return bool(np.all(stacked == stacked[0:1]))


def execute_subprocess(cmd: list[str], env: dict | None = None,
                       timeout: int | None = None) -> str:
    """Run a launch command, raise with captured output on failure
    (ref testing.py:542-561 execute_subprocess_async).

    `timeout` (default: ACCELERATE_TPU_TEST_LAUNCH_TIMEOUT or 1200 s) turns
    a wedged multi-process world into a diagnosable failure instead of a
    CI hang — a 2-process rendezvous that lost a peer blocks forever."""
    import subprocess

    if timeout is None:
        timeout = int(os.environ.get("ACCELERATE_TPU_TEST_LAUNCH_TIMEOUT",
                                     "1200"))
    merged = dict(os.environ)
    # Child processes must import accelerate_tpu even when the package is not
    # pip-installed (running from a source checkout): prepend the package's
    # parent directory to PYTHONPATH.
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    merged["PYTHONPATH"] = os.pathsep.join(
        p for p in [pkg_root, merged.get("PYTHONPATH", "")] if p
    )
    if env:
        merged.update(env)
    # own session: on timeout the WHOLE process group dies (SIGKILLing just
    # the launcher would skip its finally-block terminate and leak the
    # wedged worker ranks it spawned — still bound to the coordinator port)
    popen = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=merged,
                             start_new_session=True)
    try:
        stdout, stderr = popen.communicate(timeout=timeout)
        proc = subprocess.CompletedProcess(cmd, popen.returncode, stdout,
                                           stderr)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(popen.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, err = popen.communicate()
        raise RuntimeError(
            f"command {' '.join(cmd)} hung >{timeout}s (wedged world?)\n"
            f"--- stdout ---\n{out or ''}\n--- stderr ---\n{err or ''}"
        ) from None
    if proc.returncode != 0:
        raise RuntimeError(
            f"command {' '.join(cmd)} failed with code {proc.returncode}\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}"
        )
    return proc.stdout


def launch_command_for(script: str, num_processes: int = 1,
                       extra: list[str] | None = None) -> list[str]:
    """Build `accelerate-tpu launch` cmdline (ref get_launch_command
    testing.py:81-100)."""
    import sys

    cmd = [sys.executable, "-m", "accelerate_tpu.commands.launch"]
    if num_processes > 1:
        cmd += ["--num_processes", str(num_processes)]
    if extra:
        cmd += extra
    cmd.append(script)
    return cmd


def main_test_script_path() -> str:
    return bundled_script_path("test_script.py")


def bundled_script_path(name: str) -> str:
    """Path to a bundled launch-and-assert script under scripts/."""
    from pathlib import Path

    return str(Path(__file__).parent / "scripts" / name)


def host_values(tree):
    """Fetch a (possibly globally-sharded) pytree to host numpy on every
    process — `jax.device_get` refuses arrays spanning other hosts' devices."""
    import jax

    from ..utils.operations import _to_local

    return jax.tree_util.tree_map(_to_local, tree)
