"""Benchmark: flagship Llama train step, tokens/sec/chip + MFU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no training-throughput numbers (BASELINE.md); the
target from BASELINE.json is >=40% MFU on the causal-LM training loop, so
`vs_baseline` reports measured_MFU / 0.40.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.models.common import count_params
    from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # ~400M params: fp32 master + adam moments + grads fit one v5e chip
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
            max_position_embeddings=2048, remat=True, remat_policy="dots",
        )
        batch, seq, steps = 8, 2048, 20
    else:  # CPU smoke fallback so the bench always emits a line
        cfg = llama.LlamaConfig.tiny()
        batch, seq, steps = 4, 64, 3

    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adamw(3e-4)))
    n_params = count_params(ts.params)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch_arrays,) = list(loader)

    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
    ts, m = step(ts, batch_arrays)  # compile + warmup
    jax.block_until_ready(m["loss"])
    # best-of-3 windows: the hosted chip is shared, so a single window can
    # absorb another tenant's burst; the fastest window is the honest
    # hardware number
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, batch_arrays)
        float(m["loss"])  # forces real completion through the device tunnel
        best = min(best, time.perf_counter() - t0)
    dt = best

    n_chips = jax.device_count()
    tokens_per_step = batch * seq
    tokens_per_sec_per_chip = tokens_per_step * steps / dt / n_chips
    # 6ND causal-LM train FLOPs (fwd+bwd), + attention term
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq  # per token
    flops_per_token = 6 * n_params + attn_flops
    achieved = flops_per_token * tokens_per_sec_per_chip
    device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next(
        (v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12
    ) if on_tpu else 1e12
    mfu = achieved / peak

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "extra": {
            "mfu": round(mfu, 4),
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "steps": steps,
            "wall_s": round(dt, 2),
            "device": device_kind,
            "n_chips": n_chips,
        },
    }))


if __name__ == "__main__":
    main()
