"""Benchmark: flagship Llama train step, tokens/sec/chip + MFU.

Prints ONE JSON line:
  {"schema_version": 2, "metric": ..., "value": N, "unit": ...,
   "vs_baseline": N}
On a degraded run (dead tunnel, or operator-forced CPU) value and
vs_baseline are null — a toy CPU reading in the real metric's unit is
noise; the smoke number lives under extra.cpu_smoke_tokens_per_sec, with
the cause under "error" (outage) or "skipped" (deliberate cpu pin).

Schema v2 row contract (what BENCH_*.json trajectory tooling may rely
on; the r03-r05 tunnel-down rounds emitted extra rows with neither
metric nor unit, which is the blind spot this closes): the top-level
line AND every phase row under extra.{serving,serving_prefix,server}
carries a non-null "metric" and "unit", plus exactly ONE non-null of
"value" / "error" / "skipped" ("skipped" marks a deliberate operator
pin, not an outage — it is the third leg so tooling that retries on
"error" never retries a pin). Phase rows wrap their stats dict under
"value"; a failed phase carries the failure under "error" instead.

The reference publishes no training-throughput numbers (BASELINE.md); the
target from BASELINE.json is >=40% MFU on the causal-LM training loop, so
`vs_baseline` reports measured_MFU / 0.40.

Unkillable-by-design (the round-3 failure mode): the whole TPU bench runs
as a SUBPROCESS with a hard wall-clock ceiling, because the hosted tunnel
can either raise at init or hang indefinitely — both happened in practice.
The child IS the bench (one backend init on the happy path); if it fails,
times out, or finds no TPU, the parent re-runs the child with
JAX_PLATFORMS=cpu and emits the JSON line from the CPU smoke config,
carrying an "error" field that names the TPU failure.  Any other
exception is caught at top-level and still produces a parseable line;
exit code is always 0.  See docs/benchmarking.md for re-running after
tunnel failures.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# wall-clock ceiling for the full TPU bench child (init + compile + timed
# windows). A hung tunnel costs this once; a healthy run initializes the
# backend exactly once (the child IS the bench — no separate probe).
_TPU_TIMEOUT = int(os.environ.get("BENCH_TPU_TIMEOUT", "900"))
# per-phase ceiling for the extra rows (serving, serving_prefix, server):
# each phase is its OWN child with its own budget, so a device that wedges
# mid-phase costs that phase only — its row carries "error" and the rest
# of the line survives (BENCH_r05: one hung phase used to eat the whole
# 900s budget and the entire line with it).
_PHASE_TIMEOUT = int(os.environ.get("BENCH_PHASE_TIMEOUT", "300"))
# The tunnel has been flapping since r03: a transient drop at child-spawn
# time used to cost the whole TPU row immediately. Failed TPU attempts
# (crash or no-TPU-visible — hangs too: a flap can wedge one attempt and
# clear) now retry up to BENCH_TPU_RETRIES times with exponential backoff
# before the run is declared degraded and falls back to CPU.
_TPU_RETRIES = int(os.environ.get("BENCH_TPU_RETRIES", "2"))
_TPU_RETRY_BACKOFF_S = float(os.environ.get("BENCH_TPU_RETRY_BACKOFF_S", "5"))

# bumped whenever the one-line JSON contract changes shape; v2 = the
# per-row metric/unit + exactly-one-of-value/error/skipped guarantee
_SCHEMA_VERSION = 2

_PHASE_METRICS = {
    "serving": ("serving_offered_load", "summary"),
    "serving_prefix": ("serving_prefix_reuse", "summary"),
    "server": ("server_http_load", "summary"),
    "pod": ("serving_pod_offered_load", "summary"),
    "pod_dist": ("serving_pod_distributed", "summary"),
    "serving_spec": ("serving_speculative_ab", "summary"),
    "serving_host_tier": ("serving_host_tier_ab", "summary"),
}


def _normalize_row(row: dict, metric: str, unit: str) -> dict:
    """Enforce the schema-v2 row contract in ONE place: non-null
    metric/unit, and exactly one non-null of value/error/skipped (a row
    that produced none of them is itself an error — silence must parse
    as failure, not as success with no number)."""
    if row.get("metric") is None:
        row["metric"] = metric
    if row.get("unit") is None:
        row["unit"] = unit
    populated = [k for k in ("error", "skipped", "value")
                 if row.get(k) is not None]
    if not populated:
        row["error"] = "degraded run: no value produced"
    else:
        # precedence error > skipped > value: a value produced alongside
        # a failure (or a pin) is suspect and must not parse as a result
        for k in populated[1:]:
            row[k] = None
    return row


def _phase_row(phase: str, payload: dict) -> dict:
    """Wrap one phase child's output as a schema-v2 row: the stats dict
    rides under "value", a failure under "error"."""
    metric, unit = _PHASE_METRICS.get(phase, (f"bench_{phase}", "summary"))
    if payload.get("error") is not None:
        return _normalize_row({"error": payload["error"]}, metric, unit)
    return _normalize_row({"value": payload}, metric, unit)


def run_bench(error: str | None, require_tpu: bool = False) -> dict | None:
    """Build and time the bench; None when require_tpu and no TPU visible
    (the caller exits nonzero so the parent falls back to CPU)."""
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.models.common import count_params
    from accelerate_tpu.profiler import StepTimer
    from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS

    dev0 = jax.devices()[0]
    on_tpu = "tpu" in (
        dev0.platform + getattr(dev0, "device_kind", "")
    ).lower()
    if require_tpu and not on_tpu:
        return None
    if on_tpu:
        # ~400M params: fp32 master + adam moments + grads fit one v5e chip
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
            max_position_embeddings=2048, remat=True, remat_policy="dots",
        )
        batch, seq, steps = 8, 2048, 20
    else:  # CPU smoke fallback so the bench always emits a line
        cfg = llama.LlamaConfig.tiny()
        batch, seq, steps = 4, 64, 3

    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adamw(3e-4)))
    n_params = count_params(ts.params)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch_arrays,) = list(loader)

    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
    ts, m = step(ts, batch_arrays)  # compile + warmup
    jax.block_until_ready(m["loss"])
    # best-of-3 windows: the hosted chip is shared, so a single window can
    # absorb another tenant's burst; the fastest window is the honest
    # hardware number
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, batch_arrays)
        float(m["loss"])  # forces real completion through the device tunnel
        best = min(best, time.perf_counter() - t0)
    dt = best

    # per-step HOST dispatch cost (the python step() call returns once XLA
    # execution is enqueued): isolates the framework's steady-state overhead
    # from the compiled program's runtime. Cached dispatch should keep this
    # in single-digit microseconds per state leaf. Same meter as
    # profile_step.py and the serving engine (StepTimer), so the numbers
    # stay comparable across tools; warmup_steps=0 because the program is
    # already compiled and dispatch-cached by the timed windows above.
    timer = StepTimer(warmup_steps=0)
    for _ in range(steps):
        with timer.dispatch():
            ts, m = step(ts, batch_arrays)
    float(m["loss"])
    host_dispatch_us = timer.host_dispatch_us

    # per-step-synchronized window for the tail-latency telemetry row:
    # tick() blocks on each step's loss, so the histogram sees true
    # step times (the throughput windows above stay free-running)
    tail_timer = StepTimer(warmup_steps=0)
    tail_timer.tick()
    for _ in range(steps):
        ts, m = step(ts, batch_arrays)
        tail_timer.tick(m["loss"])
    tail_summary = tail_timer.summary()

    # goodput + measured roofline (ISSUE 11): training goodput is useful
    # step-time / wall-time from the synchronized window; the accelerator's
    # cost table carries the compiled step's FLOPs and the fence-sampled
    # device times accumulated by every dispatch above
    # only a MEASURED goodput lands in the row: defaulting a missing
    # reading to 1.0 would hand bench-diff a fabricated best-case
    # baseline that flags every later honest reading as a regression
    goodput_row = {}
    if "goodput" in tail_summary:
        goodput_row["training"] = round(tail_summary["goodput"], 4)
    train_sheet = acc.cost_table.roofline("train_step") or {}
    if "device_time_mean_s" in train_sheet:
        goodput_row["train_device_time_sampled_ms"] = round(
            train_sheet["device_time_mean_s"] * 1e3, 4)
    if "mfu" in train_sheet:
        goodput_row["train_mfu_measured"] = round(train_sheet["mfu"], 5)

    # resilient-loop smoke (ISSUE 20): the SAME compiled step through
    # run_resilient with periodic step-overlapped saves — goodput with the
    # loop on, what draining the async writer actually cost, and proof the
    # resilience plumbing recompiles nothing. A retried bench attempt
    # (BENCH_RESUME_DIR set by the parent) resumes from the previous
    # attempt's newest complete manifest instead of starting over.
    goodput_row.update(_resilience_smoke(acc, step, ts, batch_arrays, steps))

    n_chips = jax.device_count()
    tokens_per_step = batch * seq
    tokens_per_sec_per_chip = tokens_per_step * steps / dt / n_chips
    # 6ND causal-LM train FLOPs (fwd+bwd), + attention term
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq  # per token
    flops_per_token = 6 * n_params + attn_flops
    achieved = flops_per_token * tokens_per_sec_per_chip
    device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next(
        (v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12
    ) if on_tpu else 1e12
    mfu = achieved / peak

    extra = {
        "mfu": round(mfu, 4),
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "steps": steps,
        "wall_s": round(dt, 2),
        "device": device_kind,
        "n_chips": n_chips,
        "host_dispatch_us": round(host_dispatch_us, 1),
        "goodput": goodput_row,
        # telemetry row (ISSUE 3): step-time tail latency from the shared
        # streaming-histogram meter, not just means
        "telemetry": {
            "step_time_p50_s": round(tail_summary["step_time_p50_s"], 6),
            "step_time_p99_s": round(tail_summary["step_time_p99_s"], 6),
            "step_time_mean_s": round(tail_summary["mean_step_time_s"], 6),
            "host_dispatch_us_mean": round(host_dispatch_us, 1),
        },
    }
    # (the serving rows are attached by the PARENT as separate phase
    # children with their own timeouts — see _attach_phase_rows)
    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "unit": "tokens/s/chip",
        "extra": extra,
    }
    if on_tpu:
        result["value"] = round(tokens_per_sec_per_chip, 1)
        result["vs_baseline"] = round(mfu / 0.40, 3)
    else:
        # Degraded run (dead tunnel / forced CPU): a toy-config CPU number
        # in the real metric's unit is pure noise, so the headline fields
        # are nulled and the smoke reading lives under extra only.
        result["value"] = None
        result["vs_baseline"] = None
        extra["cpu_smoke_tokens_per_sec"] = round(tokens_per_sec_per_chip, 1)
    if error:
        # A deliberate operator pin is not an outage: carry it under
        # "skipped" so tooling gating on "error" (capture loop, docs
        # forensics flow) doesn't classify it as a dead tunnel and retry.
        if os.environ.get("BENCH_TPU_SKIPPED") == "1":
            result["skipped"] = error
        else:
            result["error"] = error
    return result


def _resilience_smoke(acc, step, ts, batch_arrays, steps) -> dict:
    """Fold the resilience loop into the bench (ISSUE 20): run the SAME
    compiled step through `run_resilient` with periodic async saves.
    Quotes the loop's goodput, the drain/stage costs from the telemetry
    histograms, the resume latency when an earlier attempt's commit was
    picked up, and the compile-counter deltas (must be 0 — the loop adds
    no retraces)."""
    import tempfile

    from accelerate_tpu import checkpointing as ckpt
    from accelerate_tpu.profiler import StepTimer
    from accelerate_tpu.telemetry import get_registry
    from accelerate_tpu.training import run_resilient

    ckpt_dir = os.environ.get("BENCH_RESUME_DIR") or tempfile.mkdtemp(
        prefix="bench_resilient_")
    # one-time writer setup (orbax construction, torch import) happens
    # OUTSIDE the goodput window, as a real long run would have it
    ckpt.warm_async_checkpointer()
    pins0 = getattr(step, "_pin_computations", 0)
    aot0 = getattr(step, "_aot_compiles", 0)
    timer = StepTimer(warmup_steps=1, name="bench_resilient")
    num = max(6, steps)
    rep = run_resilient(
        acc, ts, step, lambda i: batch_arrays, num, ckpt_dir,
        save_every=max(2, num // 3), keep_last_n=2, timer=timer)
    row = {
        "resilient": round(rep.goodput, 4),
        "resumes": rep.resumes,
        "saves": rep.saves,
        "attempts": int(os.environ.get("BENCH_ATTEMPT", "0")) + 1,
        "resumed_from_step": rep.start_step,
        "train_pin_computations": getattr(step, "_pin_computations", 0) - pins0,
        "train_aot_compiles": getattr(step, "_aot_compiles", 0) - aot0,
    }
    drain = get_registry().histogram("checkpoint_drain_seconds").summary()
    if drain.get("count"):
        row["checkpoint_drain_p99_s"] = round(drain["p99"], 4)
        row["checkpoint_drain_mean_s"] = round(drain["mean"], 4)
    stage = get_registry().histogram("checkpoint_stage_seconds").summary()
    if stage.get("count"):
        row["checkpoint_stage_mean_s"] = round(stage["mean"], 4)
    resume = get_registry().histogram("resume_latency_seconds").summary()
    if resume.get("count"):
        row["resume_latency_s"] = round(resume["mean"], 4)
    return row


def _load_serve_bench():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench", path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    return sb


def _serving_row() -> dict:
    """Offered-load smoke through the continuous-batching engine
    (benchmarks/serve_bench.py): tokens/sec + TTFT/per-token percentiles.
    The row names which decode attention op and KV dtype produced the
    numbers (paged_attention resolves per platform: Pallas kernel on a
    single-device TPU, dense gather on CPU) so BENCH_r* lines stay
    comparable across configs."""
    sb = _load_serve_bench()
    engine, cfg = sb.build_tiny_engine("llama", num_slots=4, max_len=128,
                                       prefill_chunk=16)
    s = sb.run_offered_load(engine, cfg.vocab_size, num_requests=12,
                            rate_hz=200.0)
    keep = ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
            "per_token_p50_ms", "per_token_p99_ms", "slot_occupancy_mean",
            "requests_finished", "requests_rejected", "kv_bytes_in_use",
            "pages_capacity",
            # roofline + goodput (ISSUE 11): what the device was doing,
            # from the engine's cost table and fence-sampled device times
            "decode_mfu", "decode_mxu_idle_fraction", "decode_hbm_bw_util",
            "decode_device_time_mean_ms", "goodput")
    row = {k: round(float(s[k]), 2) for k in keep if k in s}
    row["paged_attention"] = ("kernel" if engine._use_paged_kernel
                              else "dense")
    row["kv_dtype"] = ("int8" if engine.cache.quantized
                       else str(engine.cache.k.dtype))
    return row


def _serving_prefix_row(num_requests: int = 12, prefix_pool: int = 4,
                        prefix_len: int = 32, page_size: int = 8) -> dict:
    """Shared-prefix offered-load smoke: the paged KV cache's radix-tree
    prefix reuse under the traffic it targets — reports the hit rate and
    cached-token fraction next to the latency percentiles, so a reuse
    regression (hit rate -> 0, prefill chunks up) is visible in the same
    one-line JSON as the training row."""
    sb = _load_serve_bench()
    engine, cfg = sb.build_tiny_engine(
        "llama", num_slots=4, max_len=prefix_len + 48, prefill_chunk=16,
        page_size=page_size)
    s = sb.run_offered_load(
        engine, cfg.vocab_size, num_requests=num_requests, rate_hz=200.0,
        prompt_len=(4, 16), max_new_tokens=(4, 8),
        prefix_pool=prefix_pool, prefix_len=prefix_len)
    keep = ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
            "prefill_chunks", "prefix_hits", "prefix_hit_rate",
            "cached_token_fraction", "page_evictions", "requests_finished",
            "goodput")
    return {k: round(float(s[k]), 3) for k in keep if k in s}


def _server_row(num_requests: int = 12) -> dict:
    """Two-tenant offered-load smoke through the REAL HTTP front door
    (accelerate_tpu.server over the engine): per-tier TTFT p99 and SLO
    attainment sourced from the server's own Prometheus route, plus the
    shed (429) counts — the bench line now proves the user-facing layer,
    not just the Python engine."""
    sb = _load_serve_bench()
    specs, loads = sb.parse_tenant_load_arg(
        "gold:priority=0,weight=4,slo=0.5,rate=100;"
        "bronze:priority=1,slo=2.0,rate=100")
    engine, cfg = sb.build_tiny_engine(
        "llama", num_slots=4, max_len=128, prefill_chunk=16, tenants=specs)
    s = sb.run_http_load(
        engine, cfg.vocab_size, specs, loads, num_requests=num_requests,
        prompt_len=(4, 16), max_new_tokens=(4, 8))
    keep = ("tokens_per_sec", "requests_finished", "wall_s",
            "compiles_decode")
    row = {k: round(float(s[k]), 3) for k in keep if k in s}
    for k, v in s.items():
        if k.startswith("tenants.") and isinstance(v, (int, float)):
            row[k] = round(float(v), 4)
    return row


def _serving_spec_row(num_requests: int = 10, draft_k: int = 4) -> dict:
    """Speculative-decoding A/B smoke (ISSUE 12): the SAME seeded
    offered-load trace through the engine with speculation off
    (baseline) and on (self-draft, accept rate ~1.0) — the row quotes
    tokens-per-decode-step, the accept rate, and the before/after
    `decode_mxu_idle_fraction` (PR 11's measured number this feature
    exists to lower), plus a greedy byte-exactness verdict between the
    two arms (committed tokens must be identical under greedy)."""
    sb = _load_serve_bench()
    keep = ("tokens_per_sec", "tokens_per_decode_step", "decode_steps",
            "spec_accept_rate", "spec_drafted_tokens",
            "spec_accepted_tokens", "decode_mxu_idle_fraction",
            "decode_mfu", "decode_device_time_mean_ms", "ttft_p50_ms",
            "requests_finished")
    row: dict = {"draft_k": draft_k}
    tokens = {}
    for arm, spec in (("baseline", False), ("speculative", True)):
        engine, cfg = sb.build_tiny_engine(
            "llama", num_slots=4, max_len=128, prefill_chunk=16,
            speculative=spec, draft_k=draft_k)
        # lower the fence-sampling cadence so the short smoke actually
        # measures device time (default 16 samples ~2 windows here)
        engine.cost.sample_every = 4
        s = sb.run_offered_load(engine, cfg.vocab_size,
                                num_requests=num_requests, rate_hz=200.0,
                                prompt_len=(4, 16), max_new_tokens=(6, 12))
        row[arm] = {k: round(float(s[k]), 4) for k in keep if k in s}
        tokens[arm] = [
            list(r) for r in _collect_greedy_tokens(sb, spec, draft_k)]
    row["greedy_byte_identical"] = tokens["baseline"] == tokens["speculative"]
    return row


def _collect_greedy_tokens(sb, speculative: bool, draft_k: int):
    """A tiny fixed greedy trace through a fresh engine — the byte-
    exactness probe backing the A/B row's verdict field."""
    import numpy as np

    engine, cfg = sb.build_tiny_engine(
        "llama", num_slots=2, max_len=96, prefill_chunk=16,
        speculative=speculative, draft_k=draft_k)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 12, 9)]
    reqs = [engine.submit(p, max_new_tokens=8) for p in prompts]
    engine.run_until_idle()
    return [r.tokens for r in reqs]


def _serving_host_tier_row(num_requests: int = 24) -> dict:
    """Hierarchical-KV A/B smoke (ISSUE 16): the SAME seeded churn
    trace — a prefix pool bigger than the HBM page pool, so hot prefixes
    cycle through eviction — with the host tier off (baseline: eviction
    destroys, hits re-prefill) and on (eviction swaps out, hits swap
    back in). The row quotes prefill chunks per arm and their ratio
    (the acceptance bar is >= 2x fewer with the tier on), the swap and
    host-hit counters, plus a greedy exactness verdict: a prefix that
    round-tripped through host DRAM must continue byte-identically, in
    bf16 and int8 pools both."""
    sb = _load_serve_bench()
    keep = ("tokens_per_sec", "prefill_chunks", "prefix_hit_rate",
            "prefix_hits_hbm", "prefix_hits_host", "swap_out_pages",
            "swap_in_pages", "swap_in_p50_ms", "host_tier_pages_in_use",
            "requests_finished", "compiles_decode")
    row: dict = {}
    for arm, budget in (("baseline", 0), ("host_tier", 1 << 28)):
        engine, cfg = sb.build_tiny_engine(
            "llama", num_slots=2, max_len=160, prefill_chunk=16,
            page_size=4, num_pages=96, host_tier_bytes=budget)
        s = sb.run_offered_load(engine, cfg.vocab_size,
                                num_requests=num_requests, rate_hz=200.0,
                                prompt_len=(4, 16),
                                max_new_tokens=(4, 8),
                                prefix_pool=6, prefix_len=112, seed=0)
        row[arm] = {k: round(float(s[k]), 4) for k in keep if k in s}
    base_chunks = row["baseline"].get("prefill_chunks", 0.0)
    tier_chunks = row["host_tier"].get("prefill_chunks", 0.0)
    if tier_chunks:
        row["prefill_chunk_ratio"] = round(base_chunks / tier_chunks, 3)
    row["greedy_byte_identical"] = all(
        _host_tier_round_trip_exact(sb, kv) for kv in (None, "int8"))
    return row


def _host_tier_round_trip_exact(sb, kv_dtype) -> bool:
    """Greedy exactness probe: decode a prompt cold, churn its pages out
    to the host tier, decode it again through the swap-in path — the
    tokens must match, and a swap-in must actually have happened (a
    probe that silently skipped the round trip proves nothing)."""
    import numpy as np

    engine, _cfg = sb.build_tiny_engine(
        "llama", num_slots=2, max_len=64, prefill_chunk=8, page_size=4,
        num_pages=18, host_tier_bytes=1 << 28, kv_dtype=kv_dtype)
    rng = np.random.default_rng(11)
    pA, pB, pC = (rng.integers(0, _cfg.vocab_size, (33,)).astype(np.int32)
                  for _ in range(3))
    cold = engine.submit(pA, max_new_tokens=6)
    engine.run_until_idle()
    for p in (pB, pC):                      # churn A's pages to the tier
        engine.submit(p, max_new_tokens=6)
        engine.run_until_idle()
    warm = engine.submit(pA, max_new_tokens=6)
    engine.run_until_idle()
    swapped = engine.metrics.swap_in_pages > 0
    engine.close()
    return swapped and list(cold.tokens) == list(warm.tokens)


def _pod_row(num_requests: int = 10) -> dict:
    """Disaggregated-pod offered-load smoke (ISSUE 9): one prefill + one
    decode worker with KV pages shipping between them, behind the same
    submit/stream surface — reports the shipment counters and the
    per-role compile counts next to the latency percentiles, so a pod
    regression (shipments -> 0, compiles creeping) is visible in the
    same one-line JSON as the training row."""
    sb = _load_serve_bench()
    engine, cfg = sb.build_tiny_pod_engine(
        "llama", pod_roles=(1, 1), num_slots=4, max_len=128,
        prefill_chunk=16)
    s = sb.run_offered_load(engine, cfg.vocab_size,
                            num_requests=num_requests, rate_hz=200.0,
                            prompt_len=(4, 16), max_new_tokens=(4, 8))
    keep = ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
            "per_token_p50_ms", "requests_finished", "pod_shipments",
            "pod_pages_shipped", "pod_backpressure_stalls",
            "compiles_decode", "compiles_install", "compiles_extract")
    return {k: round(float(s[k]), 3) for k in keep if k in s}


def _pod_dist_row(num_requests: int = 8) -> dict:
    """TRUE multi-host pod offered-load smoke (ISSUE 17): the same
    offered-load trace as the in-process pod row, but through
    `DistributedPodRouter` with one prefill + one decode worker as REAL
    OS processes shipping KV pages over TCP — the A/B against the "pod"
    row prices the wire + process boundary. Reports the shipment and
    recovery counters (workers_lost / requests_replayed must be 0 on a
    healthy run) next to the latency percentiles."""
    sb = _load_serve_bench()
    engine, cfg, procs = sb.build_tiny_distributed_pod(
        "llama", pod_roles=(1, 1), num_slots=4, max_len=128,
        prefill_chunk=16)
    try:
        s = sb.run_offered_load(engine, cfg.vocab_size,
                                num_requests=num_requests, rate_hz=200.0,
                                prompt_len=(4, 16), max_new_tokens=(4, 8))
    finally:
        engine.close()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except Exception:
                proc.kill()
    keep = ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
            "per_token_p50_ms", "requests_finished", "pod_shipments",
            "pod_pages_shipped", "pod_backpressure_stalls",
            "pod_workers_lost", "pod_workers_recovered",
            "pod_requests_replayed", "pod_stale_messages",
            "pod_role_conversions", "pod_recovery_latency_p50_ms",
            "pod_recovery_latency_p99_ms",
            "compiles_decode", "compiles_install", "compiles_extract")
    row = {k: round(float(s[k]), 3) for k in keep if k in s}
    row["transport"] = "socket"
    return row


def _child_main() -> None:
    """Runs inside a bench child process (BENCH_CHILD=1). BENCH_PHASE
    selects which phase this child IS: "train" (default, the full
    training bench) or one of the serving rows — each phase child owns
    exactly one backend init and one failure domain."""
    phase = os.environ.get("BENCH_PHASE", "train") or "train"
    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if on_cpu:
        # the hosted image pins jax_platforms to the tunnel backend at
        # import time, silently overriding the env var — force CPU via the
        # config before any backend initializes (tests/conftest.py fix)
        from accelerate_tpu.utils.environment import force_cpu_platform

        force_cpu_platform()
    if phase in ("serving", "serving_prefix", "server", "pod", "pod_dist",
                 "serving_spec", "serving_host_tier"):
        if not on_cpu:
            # spawned on the TPU-success path: if the tunnel dropped
            # after the train child, jax would silently fall back to CPU
            # and this row would report CPU numbers under a TPU headline
            # — exit 3 so the parent reports it in the row's error field
            import jax

            dev0 = jax.devices()[0]
            if "tpu" not in (
                    dev0.platform + getattr(dev0, "device_kind", "")).lower():
                sys.exit(3)
        row = {"serving": _serving_row,
               "serving_prefix": _serving_prefix_row,
               "server": _server_row,
               "pod": _pod_row,
               "pod_dist": _pod_dist_row,
               "serving_spec": _serving_spec_row,
               "serving_host_tier": _serving_host_tier_row}[phase]()
        print(json.dumps(row))
        return
    if on_cpu:
        print(json.dumps(run_bench(os.environ.get("BENCH_TPU_ERROR") or None)))
        return
    result = run_bench(None, require_tpu=True)
    if result is None:
        sys.exit(3)  # no TPU visible; parent falls back to CPU
    print(json.dumps(result))


def _last_json_line(text: str) -> str | None:
    return next(
        (ln for ln in reversed(text.splitlines()) if ln.startswith("{")),
        None,
    )


def _spawn_child(phase: str, timeout: int, **env_overrides):
    """Run bench.py as a BENCH_CHILD subprocess — one phase, one backend
    init, one failure domain. The single place that knows the child
    protocol (env assembly, JSON-line extraction, error-tail capture).
    Returns (returncode, last JSON line or None, one-line error tail);
    TimeoutExpired propagates — each caller owns its hang message."""
    env = {**os.environ, "BENCH_CHILD": "1", "BENCH_PHASE": phase,
           **env_overrides}
    out = subprocess.run([sys.executable, __file__], env=env,
                         capture_output=True, text=True, timeout=timeout)
    tail = (out.stderr or out.stdout).strip().splitlines()
    return (out.returncode, _last_json_line(out.stdout),
            tail[-1][:300] if tail else "no output")


def _run_phase(phase: str, cpu: bool) -> dict:
    """One extra-row phase in its own child with its own timeout: a
    wedged device (or a crash) yields a row with "error" populated, never
    a hang or a poisoned line — each phase is failure-isolated."""
    try:
        rc, line, tail = _spawn_child(
            phase, _PHASE_TIMEOUT, JAX_PLATFORMS="cpu" if cpu else "")
        if rc == 0 and line:
            return json.loads(line)
        if rc == 3:
            return {"error": f"{phase} bench skipped: no tpu visible "
                    "(tunnel dropped after the train phase)"}
        return {"error": f"{phase} bench failed: {tail}"}
    except subprocess.TimeoutExpired:
        return {"error": f"{phase} bench hung >{_PHASE_TIMEOUT}s "
                "(tunnel unresponsive)"}


def _emit(payload: dict, cpu: bool) -> None:
    """Attach the serving phase rows (each its own timed child), enforce
    the schema-v2 row contract on every row, and print the one contract
    line."""
    if os.environ.get("BENCH_SERVING", "1") == "1":
        extra = payload.setdefault("extra", {})
        extra["serving"] = _phase_row("serving", _run_phase("serving", cpu))
        extra["serving_prefix"] = _phase_row(
            "serving_prefix", _run_phase("serving_prefix", cpu))
        extra["server"] = _phase_row("server", _run_phase("server", cpu))
        extra["pod"] = _phase_row("pod", _run_phase("pod", cpu))
        extra["pod_dist"] = _phase_row("pod_dist", _run_phase("pod_dist", cpu))
        extra["serving_spec"] = _phase_row(
            "serving_spec", _run_phase("serving_spec", cpu))
        extra["serving_host_tier"] = _phase_row(
            "serving_host_tier", _run_phase("serving_host_tier", cpu))
    _normalize_row(payload, "llama_train_tokens_per_sec_per_chip",
                   "tokens/s/chip")
    payload["schema_version"] = _SCHEMA_VERSION
    print(json.dumps(payload))


def main() -> None:
    if os.environ.get("BENCH_CHILD") == "1":
        _child_main()
        return
    # The parent never initializes JAX. The TPU attempt runs as a killable
    # child (the tunnel can hang at init, not just fail) and IS the full
    # bench — one backend init on the happy path, no separate probe.
    error = None
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # operator explicitly forced CPU — don't pay the TPU hang budget
        _emit(_run_cpu_fallback(
            "JAX_PLATFORMS=cpu set by operator; tpu attempt skipped",
            skipped=True,
        ), cpu=True)
        return
    # bounded retry-with-backoff: the tunnel flaps (down since r03, and a
    # transient drop used to cost the whole TPU row on the spot) — only
    # after every attempt fails is the run declared degraded. All attempts
    # share one resume dir: an attempt killed mid-run leaves its newest
    # COMPLETE manifest behind, and the retry's resilient loop picks it up
    # instead of starting over (extra.goodput.attempts/resumed_from_step
    # record that it happened).
    import tempfile

    resume_dir = tempfile.mkdtemp(prefix="bench_resume_")
    for attempt in range(_TPU_RETRIES + 1):
        try:
            rc, line, tail = _spawn_child("train", _TPU_TIMEOUT,
                                          JAX_PLATFORMS="",
                                          BENCH_ATTEMPT=str(attempt),
                                          BENCH_RESUME_DIR=resume_dir)
            if rc == 0 and line:
                _emit(json.loads(line), cpu=False)
                return
            if rc == 3:
                error = "no tpu visible (tunnel backend came up without one)"
            else:
                error = f"tpu bench failed: {tail}"
        except subprocess.TimeoutExpired:
            error = f"tpu bench hung >{_TPU_TIMEOUT}s (tunnel unresponsive)"
        if attempt < _TPU_RETRIES:
            time.sleep(_TPU_RETRY_BACKOFF_S * (2 ** attempt))
    if _TPU_RETRIES:
        error = f"{error} (after {_TPU_RETRIES + 1} attempts)"
    _emit(_run_cpu_fallback(error), cpu=True)


def _run_cpu_fallback(error: str, skipped: bool = False) -> dict:
    """TPU unusable: CPU child so no poisoned backend state survives.
    The child nulls value/vs_baseline (degraded runs carry no headline
    number — only extra.cpu_smoke_tokens_per_sec and the error field).
    skipped=True marks a deliberate operator pin, reported under
    "skipped" rather than "error". Returns the payload dict (the caller
    attaches phase rows and prints)."""
    env_extra = {"JAX_PLATFORMS": "cpu", "BENCH_TPU_ERROR": error}
    if skipped:
        env_extra["BENCH_TPU_SKIPPED"] = "1"
    _, line, tail = _spawn_child("train", 900, **env_extra)
    if line:
        return json.loads(line)
    # last resort: the contract line, hand-built
    return {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
        "error": error,
        "fallback_stderr": tail,
    }


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # absolute last resort — still one parseable line
        print(json.dumps({
            "schema_version": _SCHEMA_VERSION,
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
            "error": f"{type(e).__name__}: {str(e)[:300]}",
        }))
    sys.exit(0)
