"""Complete CV example: convnet classification + tracking + checkpointing +
resume (ref examples/complete_cv_example.py).

Same loop as cv_example.py with --with_tracking, --checkpointing_steps and
--resume_from_checkpoint layered on, mirroring the reference's complete
variant feature-for-feature.
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.utils import ProjectConfiguration, set_seed

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cv_example import (  # noqa: E402
    convnet_forward,
    get_dataloaders,
    init_convnet,
    loss_fn,
)


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_clipping=1.0,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir or ".",
            automatic_checkpoint_naming=True,
        ),
    )
    set_seed(args.seed)
    train_loader, eval_loader = get_dataloaders(accelerator, args.batch_size)
    params = init_convnet(jax.random.key(args.seed), width=args.width)
    ts = accelerator.prepare(
        TrainState.create(apply_fn=None, params=params, tx=optax.adamw(args.lr))
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    starting_epoch = resume_step = 0
    if args.resume_from_checkpoint:
        restored = accelerator.load_state(
            None if args.resume_from_checkpoint == "latest"
            else args.resume_from_checkpoint, state=ts,
        )
        ts = restored.get("train_states", [ts])[0]
        done = int(ts.step)
        starting_epoch, resume_step = divmod(done, len(train_loader))
        accelerator.print(f"resumed at epoch {starting_epoch}, batch {resume_step}")

    step = accelerator.train_step(loss_fn)
    eval_step = accelerator.eval_step(
        lambda p, b: jnp.argmax(convnet_forward(p, b["pixels"]), -1)
    )

    overall_step = int(ts.step)
    metrics = {}
    for epoch in range(starting_epoch, args.num_epochs):
        loader = train_loader
        if epoch == starting_epoch and resume_step > 0:
            loader = accelerator.skip_first_batches(train_loader, resume_step)
        total = 0.0
        for batch in loader:
            ts, m = step(ts, batch)
            total += float(m["loss"])
            overall_step += 1
            if isinstance(args.checkpointing_steps, int) and (
                overall_step % args.checkpointing_steps == 0
            ):
                accelerator.save_state(state=ts)
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(state=ts)
        correct = tot = 0
        for batch in eval_loader:
            preds = eval_step(ts.params, batch)
            preds, labels = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            tot += int(np.asarray(labels).shape[0])
        metrics = {"epoch": epoch, "train_loss": total / max(1, len(train_loader)),
                   "accuracy": correct / tot}
        accelerator.print(f"epoch {epoch}: {metrics}")
        if args.with_tracking:
            accelerator.log(metrics, step=overall_step)
    if args.with_tracking:
        accelerator.end_training()
    return metrics


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--width", type=int, default=32)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--checkpointing_steps", default=None)
    parser.add_argument("--resume_from_checkpoint", default=None)
    args = parser.parse_args(argv)
    if args.checkpointing_steps and args.checkpointing_steps != "epoch":
        args.checkpointing_steps = int(args.checkpointing_steps)
    return args


if __name__ == "__main__":
    training_function(parse_args())
