"""NLP example: BERT sequence classification, accelerate_tpu-style.

Mirror of ref examples/nlp_example.py (BERT-base on GLUE/MRPC): the user owns
the loop; the Accelerator owns distribution, precision, accumulation, metrics
gathering. Zero-egress environments get a synthetic MRPC-shaped dataset;
pass --glue to use HF datasets/transformers when available.

Run: python examples/nlp_example.py [--mixed_precision bf16] [--fsdp]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import bert
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

EVAL_BATCHES = 4


def synthetic_mrpc(vocab_size: int, n: int = 512, seq: int = 128, seed: int = 0):
    """MRPC-shaped synthetic pairs: label correlates with token overlap so the
    model has signal to learn."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(4, vocab_size, (n, seq)).astype(np.int32)
    labels = rng.integers(0, 2, (n,)).astype(np.int32)
    # inject signal: positive pairs repeat a sentinel pattern
    ids[labels == 1, 4:12] = np.arange(20, 28)
    token_type = np.zeros((n, seq), np.int32)
    token_type[:, seq // 2 :] = 1
    mask = np.ones((n, seq), np.int32)
    return {"input_ids": ids, "token_type_ids": token_type,
            "attention_mask": mask, "labels": labels}


def get_dataloaders(accelerator: Accelerator, batch_size: int, cfg: bert.BertConfig):
    data = synthetic_mrpc(cfg.vocab_size)
    n_eval = EVAL_BATCHES * batch_size
    train = {k: v[:-n_eval] for k, v in data.items()}
    eval_ = {k: v[-n_eval:] for k, v in data.items()}

    def to_batches(d):
        n = len(d["labels"])
        return [
            {k: v[i : i + batch_size] for k, v in d.items()}
            for i in range(0, n, batch_size)
        ]

    return (
        accelerator.prepare_data_loader(to_batches(train)),
        accelerator.prepare_data_loader(to_batches(eval_)),
    )


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        fsdp_plugin=FullyShardedDataParallelPlugin() if args.fsdp else None,
        gradient_clipping=1.0,
        log_with="jsonl" if args.project_dir else None,
        project_dir=args.project_dir,
    )
    set_seed(args.seed)
    cfg = bert.BertConfig.tiny() if args.tiny else bert.BertConfig.base()
    train_loader, eval_loader = get_dataloaders(accelerator, args.batch_size, cfg)

    params = bert.init_params(cfg, jax.random.key(args.seed))
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, 10, args.num_epochs * len(train_loader)
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(schedule),
        use_grad_accum_buffer=args.gradient_accumulation_steps > 1,
    ))
    if args.project_dir:
        accelerator.init_trackers("nlp_example", config=vars(args))

    step = accelerator.train_step(lambda p, b: bert.classification_loss(cfg, p, b))
    eval_step = accelerator.eval_step(
        lambda p, b: jnp.argmax(
            bert.forward(cfg, p, b["input_ids"], b["attention_mask"],
                         b["token_type_ids"]), axis=-1)
    )

    metrics = {}
    for epoch in range(args.num_epochs):
        for batch in train_loader:
            ts, m = step(ts, batch)
        correct = total = 0
        for batch in eval_loader:
            preds = eval_step(ts.params, batch)
            preds, labels = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += int(np.asarray(labels).shape[0])
        metrics = {"epoch": epoch, "loss": float(m["loss"]), "accuracy": correct / total}
        accelerator.print(f"epoch {epoch}: {metrics}")
        if args.project_dir:
            accelerator.log(metrics, step=int(ts.step))
    if args.project_dir:
        accelerator.end_training()
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--fsdp", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="tiny model (CI)")
    parser.add_argument("--project_dir", default=None)
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
