"""CV example: small convnet image classification, accelerate_tpu-style.

Mirror of ref examples/cv_example.py (ResNet-50 on a pets folder): the loop is
the user's; the Accelerator handles distribution/precision/metrics. Synthetic
class-conditional images stand in for the dataset in zero-egress environments.

The model is a plain functional conv stack: NHWC layout + channels-last convs
so XLA tiles the convolutions straight onto the MXU.

Run: python examples/cv_example.py [--mixed_precision bf16]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.utils import set_seed

NUM_CLASSES = 10


def synthetic_images(n: int = 640, size: int = 32, seed: int = 0):
    """Class-conditional blobs: each class lights up a distinct image region,
    so a convnet has real signal to learn."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, NUM_CLASSES, (n,)).astype(np.int32)
    imgs = rng.normal(scale=0.3, size=(n, size, size, 3)).astype(np.float32)
    cell = size // 4
    for i, y in enumerate(labels):
        r, c = divmod(int(y) % 16, 4)
        imgs[i, r * cell : (r + 1) * cell, c * cell : (c + 1) * cell, :] += 1.5
    return imgs, labels


def init_convnet(key, width: int = 32):
    k = jax.random.split(key, 5)
    he = jax.nn.initializers.he_normal()
    return {
        "conv1": {"kernel": he(k[0], (3, 3, 3, width)), "bias": jnp.zeros((width,))},
        "conv2": {"kernel": he(k[1], (3, 3, width, width * 2)), "bias": jnp.zeros((width * 2,))},
        "conv3": {"kernel": he(k[2], (3, 3, width * 2, width * 4)), "bias": jnp.zeros((width * 4,))},
        "head": {"kernel": he(k[3], (width * 4, NUM_CLASSES)), "bias": jnp.zeros((NUM_CLASSES,))},
    }


def convnet_forward(params, images):
    x = images
    for name in ("conv1", "conv2", "conv3"):
        x = jax.lax.conv_general_dilated(
            x, params[name]["kernel"], window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params[name]["bias"]
        x = jax.nn.relu(x)
    x = x.mean(axis=(1, 2))  # global average pool
    return x @ params["head"]["kernel"] + params["head"]["bias"]


def loss_fn(params, batch):
    logits = convnet_forward(params, batch["pixels"])
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()


def get_dataloaders(accelerator: Accelerator, batch_size: int):
    imgs, labels = synthetic_images()
    n_eval = 4 * batch_size
    mean, std = imgs[:-n_eval].mean(), imgs[:-n_eval].std()
    imgs = (imgs - mean) / std

    def to_batches(lo, hi):
        return [
            {"pixels": imgs[i : i + batch_size], "labels": labels[i : i + batch_size]}
            for i in range(lo, hi, batch_size)
        ]

    return (
        accelerator.prepare_data_loader(to_batches(0, len(imgs) - n_eval)),
        accelerator.prepare_data_loader(to_batches(len(imgs) - n_eval, len(imgs))),
    )


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, gradient_clipping=1.0
    )
    set_seed(args.seed)
    train_loader, eval_loader = get_dataloaders(accelerator, args.batch_size)
    params = init_convnet(jax.random.key(args.seed), width=args.width)
    ts = accelerator.prepare(
        TrainState.create(apply_fn=None, params=params, tx=optax.adamw(args.lr))
    )
    step = accelerator.train_step(loss_fn)
    eval_step = accelerator.eval_step(
        lambda p, b: jnp.argmax(convnet_forward(p, b["pixels"]), -1)
    )

    metrics = {}
    for epoch in range(args.num_epochs):
        for batch in train_loader:
            ts, m = step(ts, batch)
        correct = total = 0
        for batch in eval_loader:
            preds = eval_step(ts.params, batch)
            preds, labels = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += int(np.asarray(labels).shape[0])
        metrics = {"epoch": epoch, "loss": float(m["loss"]), "accuracy": correct / total}
        accelerator.print(f"epoch {epoch}: {metrics}")
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--width", type=int, default=32)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
