"""Inference example: GSPMD-sharded generation + process-split serving
(ref examples/inference/distributed_inference.py — splits prompts across
GPUs with `split_between_processes`; and the pippy/ llama scripts — stage
pipelining, which on TPU is `prepare_sharded_inference`).

Two modes:
- `--mode split`: each host process takes its slice of the prompt list
  (`split_between_processes`) and decodes locally — throughput serving.
- `--mode gspmd`: one model sharded over the whole mesh (tensor-parallel
  `model` axis), all devices cooperate per token — latency serving for
  models too big for one chip.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.inference import prepare_sharded_inference
from accelerate_tpu.models import llama
from accelerate_tpu.utils import MeshConfig, set_seed


def fake_prompts(n: int, seq: int, vocab: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return rng.integers(4, vocab, (n, seq)).astype(np.int32)


def run_split(args, cfg):
    accelerator = Accelerator()
    set_seed(args.seed)
    params = llama.init_params(cfg, jax.random.key(args.seed))
    prompts = [p for p in fake_prompts(8, args.prompt_len, cfg.vocab_size)]
    with accelerator.split_between_processes(prompts) as my_prompts:
        batch = np.stack(my_prompts)
        out = llama.generate(
            cfg, params, batch, max_new_tokens=args.max_new_tokens
        )
    gathered = accelerator.gather_for_metrics(list(np.asarray(out)),
                                              use_gather_object=True)
    accelerator.print(f"decoded {len(gathered)} continuations "
                      f"(each {np.asarray(gathered[0]).shape[-1]} tokens)")
    return gathered


def run_gspmd(args, cfg):
    accelerator = Accelerator(
        mesh_config=MeshConfig(axes={"data": -1, "model": args.tp})
        if args.tp > 1 else None
    )
    set_seed(args.seed)
    params = llama.init_params(cfg, jax.random.key(args.seed))

    def forward(p, ids):
        return llama.forward(cfg, p, ids)

    fwd, sharded = prepare_sharded_inference(forward, params, mesh=accelerator.mesh)
    ids = fake_prompts(4, args.prompt_len, cfg.vocab_size)
    logits = fwd(sharded, ids)
    accelerator.print(f"sharded forward: logits {logits.shape}, "
                      f"mesh {dict(accelerator.mesh.shape)}")
    return logits


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mode", default="split", choices=["split", "gspmd"])
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--prompt_len", type=int, default=32)
    parser.add_argument("--max_new_tokens", type=int, default=16)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--tiny", action="store_true")
    args = parser.parse_args()
    cfg = llama.LlamaConfig.tiny() if args.tiny else llama.LlamaConfig(
        hidden_size=512, intermediate_size=1408, num_hidden_layers=4,
        num_attention_heads=8, num_key_value_heads=8,
    )
    if args.mode == "split":
        run_split(args, cfg)
    else:
        run_gspmd(args, cfg)


if __name__ == "__main__":
    main()
