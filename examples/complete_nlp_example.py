"""Complete NLP example: everything the simple one has, plus experiment
tracking, versioned checkpointing, and mid-epoch resume.

Mirror of ref examples/complete_nlp_example.py: adds --with_tracking,
--checkpointing_steps {N|"epoch"}, --resume_from_checkpoint on top of the
BERT classification loop. The user still owns the loop.

Run: python examples/complete_nlp_example.py --checkpointing_steps epoch \
         --with_tracking --project_dir /tmp/nlp_out
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import bert
from accelerate_tpu.utils import ProjectConfiguration, set_seed

import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from nlp_example import get_dataloaders, synthetic_mrpc  # noqa: E402,F401


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        gradient_clipping=1.0,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir or ".",
            automatic_checkpoint_naming=True,
            total_limit=args.checkpoint_total_limit,
        ),
    )
    set_seed(args.seed)
    cfg = bert.BertConfig.tiny() if args.tiny else bert.BertConfig.base()
    train_loader, eval_loader = get_dataloaders(accelerator, args.batch_size, cfg)

    params = bert.init_params(cfg, jax.random.key(args.seed))
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, 10, args.num_epochs * len(train_loader)
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(schedule),
        use_grad_accum_buffer=args.gradient_accumulation_steps > 1,
    ))
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", config=vars(args))

    starting_epoch, resume_step = 0, 0
    if args.resume_from_checkpoint:
        restored = accelerator.load_state(
            None if args.resume_from_checkpoint == "latest"
            else args.resume_from_checkpoint,
            state=ts,
        )
        ts = restored.get("train_states", [ts])[0]
        # dirs are named checkpoint_{n}; map n back to epoch/step position
        done_steps = int(ts.step)
        starting_epoch = done_steps // len(train_loader)
        resume_step = done_steps % len(train_loader)
        accelerator.print(
            f"resumed at epoch {starting_epoch}, batch {resume_step}"
        )

    step = accelerator.train_step(lambda p, b: bert.classification_loss(cfg, p, b))
    eval_step = accelerator.eval_step(
        lambda p, b: jnp.argmax(
            bert.forward(cfg, p, b["input_ids"], b["attention_mask"],
                         b["token_type_ids"]), axis=-1)
    )

    overall_step = int(ts.step)
    metrics = {}
    for epoch in range(starting_epoch, args.num_epochs):
        total_loss = 0.0
        loader = train_loader
        if epoch == starting_epoch and resume_step > 0:
            loader = accelerator.skip_first_batches(train_loader, resume_step)
        for batch in loader:
            ts, m = step(ts, batch)
            total_loss += float(m["loss"])
            overall_step += 1
            if isinstance(args.checkpointing_steps, int) and (
                overall_step % args.checkpointing_steps == 0
            ):
                accelerator.save_state(state=ts)
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(state=ts)

        correct = total = 0
        for batch in eval_loader:
            preds = eval_step(ts.params, batch)
            preds, labels = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += int(np.asarray(labels).shape[0])
        metrics = {
            "epoch": epoch,
            "train_loss": total_loss / max(1, len(train_loader)),
            "accuracy": correct / total,
        }
        accelerator.print(f"epoch {epoch}: {metrics}")
        if args.with_tracking:
            accelerator.log(metrics, step=overall_step)

    if args.with_tracking:
        accelerator.end_training()
    return metrics


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--tiny", action="store_true", help="tiny model (CI)")
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--checkpointing_steps", default=None,
                        help='save every N steps, or "epoch"')
    parser.add_argument("--checkpoint_total_limit", type=int, default=None)
    parser.add_argument("--resume_from_checkpoint", default=None,
                        help='checkpoint dir, or "latest"')
    args = parser.parse_args(argv)
    if args.checkpointing_steps and args.checkpointing_steps != "epoch":
        args.checkpointing_steps = int(args.checkpointing_steps)
    return args


if __name__ == "__main__":
    training_function(parse_args())
