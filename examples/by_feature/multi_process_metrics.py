"""Feature: exact distributed eval metrics
(ref by_feature/multi_process_metrics.py).

The sharded eval loader pads the last uneven batch so SPMD steps stay in
lockstep; `gather_for_metrics` drops those duplicated tail samples again, so
the metric sees each example EXACTLY once regardless of world size.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_forward,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    # 100 eval samples: NOT divisible by typical world sizes on purpose
    train_ds = RegressionDataset(length=256, seed=args.seed)
    eval_ds = RegressionDataset(length=100, seed=args.seed + 1)
    bs = args.batch_size
    train_loader = accelerator.prepare(
        [{"x": train_ds.x[i : i + bs], "y": train_ds.y[i : i + bs]}
         for i in range(0, 256, bs)]
    )
    eval_loader = accelerator.prepare(
        [{"x": eval_ds.x[i : i + bs], "y": eval_ds.y[i : i + bs]}
         for i in range(0, 100, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
    ))
    step = accelerator.train_step(regression_loss)
    eval_step = accelerator.eval_step(
        lambda p, b: regression_forward(p, b["x"])
    )

    for epoch in range(args.num_epochs):
        for batch in train_loader:
            ts, _ = step(ts, batch)

    preds, targets = [], []
    for batch in eval_loader:
        out = eval_step(ts.params, batch)
        out, y = accelerator.gather_for_metrics((out, batch["y"]))
        preds.append(np.asarray(out).reshape(-1))
        targets.append(np.asarray(y).reshape(-1))
    preds = np.concatenate(preds)
    targets = np.concatenate(targets)
    assert preds.shape[0] == len(eval_ds), (
        f"metric saw {preds.shape[0]} samples, dataset has {len(eval_ds)}"
    )
    metrics = {"eval_mse": float(((preds - targets) ** 2).mean()),
               "samples_seen": int(preds.shape[0])}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
