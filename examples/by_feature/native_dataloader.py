"""Feature: native (C++) token data loader feeding LM pretraining.

The C++ core (`accelerate_tpu/_native/token_loader.cpp`) memory-maps the
token file and assembles shuffled host-sharded batches on producer threads,
so batch prep overlaps the device step — the native replacement for the
reference's DataLoader worker processes / MpDeviceLoader threads. Falls back
to NumPy with identical semantics where no toolchain exists.
"""

from __future__ import annotations

import argparse
import os
import tempfile

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.native import TokenCorpusLoader, is_available, write_token_file
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(mixed_precision=args.mixed_precision,
                              gradient_clipping=1.0)
    set_seed(args.seed)
    cfg = llama.LlamaConfig.tiny() if args.tiny else llama.LlamaConfig(
        hidden_size=512, intermediate_size=1408, num_hidden_layers=4,
        num_attention_heads=8, num_key_value_heads=8,
    )
    accelerator.print(f"native loader available: {is_available()}")

    if args.token_file is None:
        # synthesize a corpus for the demo
        rng = np.random.default_rng(args.seed)
        tmp = tempfile.mkdtemp()
        args.token_file = os.path.join(tmp, "corpus.bin")
        write_token_file(
            args.token_file,
            rng.integers(0, cfg.vocab_size, size=256 * (args.seq_len + 1),
                         dtype=np.int32),
        )

    src = TokenCorpusLoader(
        args.token_file,
        sample_len=args.seq_len + 1,  # inputs + shifted targets
        batch_size=args.batch_size,
        seed=args.seed,
        rank=accelerator.process_index,
        world=accelerator.num_processes,
        threads=args.loader_threads,
    )
    loader = accelerator.prepare(src)
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=llama.init_params(cfg, jax.random.key(args.seed)),
        tx=optax.adamw(args.lr),
    ))
    step = accelerator.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))

    for epoch in range(args.num_epochs):
        src.set_epoch(epoch)
        for batch in loader:
            ts, m = step(ts, batch)
        accelerator.print({"epoch": epoch, "lm_loss": float(m["loss"])})
    return {"lm_loss": float(m["loss"])}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--token_file", default=None,
                        help="flat binary token file (int32/uint16)")
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--loader_threads", type=int, default=2)
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
