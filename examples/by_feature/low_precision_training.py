"""Feature: low-precision training — fp8 matmuls + int8 Adam moments.

The reference reaches fp8 through transformer-engine kwargs and 8-bit Adam
through bitsandbytes (ref accelerator.py fp8 recipe handling, utils/bnb.py);
here both are native: `mixed_precision="fp8"` drives the delayed-scaling
fp8 path of any bundled model (the loss fn takes an `fp8_state` kwarg and
returns `(loss, new_fp8_state)`), and `accelerate_tpu.adamw_8bit` stores
Adam moments as int8 blocks (~2.06 bytes/param), the recipe that fits
multi-billion-parameter training on one 16 GB chip
(docs/performance.md, benchmarks/mfu_table.py 1.5B/2B rows).

Run: python examples/by_feature/low_precision_training.py [--no_fp8]
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import TrainState, adamw_8bit
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.utils import FP8RecipeKwargs, set_seed


def training_function(args) -> dict:
    use_fp8 = not args.no_fp8
    accelerator = Accelerator(
        mixed_precision="fp8" if use_fp8 else "bf16",
        gradient_clipping=1.0,
        # the recipe handler reaches every family's init_fp8_state
        kwargs_handlers=[FP8RecipeKwargs(amax_history_len=16)],
    )
    set_seed(args.seed)

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(args.seed))
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=params,
        tx=adamw_8bit(args.lr, weight_decay=0.01),   # int8 moments
        fp8_state=llama.init_fp8_state(cfg) if use_fp8 else None,
    ))

    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, (args.batch_size, 65)).astype(np.int32)
    loader = accelerator.prepare([{"input_ids": ids}] * 8)

    if use_fp8:
        step = accelerator.train_step(
            lambda p, b, fp8_state=None: llama.causal_lm_loss(
                cfg, p, b, fp8_state=fp8_state))
    else:
        step = accelerator.train_step(
            lambda p, b: llama.causal_lm_loss(cfg, p, b))
    losses = []
    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, metrics = step(ts, batch)
            losses.append(float(metrics["loss"]))
        accelerator.print(f"epoch {epoch}: loss {losses[-1]:.4f}")
    if use_fp8:
        # delayed-scaling state really adapted
        scale = ts.fp8_state["layers"]["attn"]["q_proj"]["x"].scale
        accelerator.print(f"fp8 q_proj x-scale (per layer): {np.asarray(scale)}")
    return {"first_loss": losses[0], "last_loss": losses[-1]}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--no_fp8", action="store_true",
                        help="bf16 matmuls (int8 moments either way)")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=5e-3)
    parser.add_argument("--seed", type=int, default=42)
    out = training_function(parser.parse_args())
    assert out["last_loss"] < out["first_loss"], out
    print("low_precision_training OK:", out)


if __name__ == "__main__":
    main()
