"""Feature: cross-host early stopping (ref by_feature/early_stopping.py).

Any host that meets the stop condition calls `set_trigger()`; every host
polls `check_trigger()` (a flag all-reduce) so ALL ranks break on the same
step — no rank ever waits on a collective the others skipped.
"""

from __future__ import annotations

import argparse

import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    ds = RegressionDataset(length=512, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 512, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
    ))
    step = accelerator.train_step(regression_loss)

    stopped_at = None
    steps = 0
    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)
            steps += 1
            if float(m["loss"]) < args.loss_threshold:
                accelerator.set_trigger()
            # flag all-reduce: True if ANY process triggered
            if accelerator.check_trigger():
                stopped_at = steps
                break
        if stopped_at is not None:
            break

    metrics = {"loss": float(m["loss"]), "stopped_at_step": stopped_at}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--loss_threshold", type=float, default=0.05)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
