"""Feature: automatic gradient accumulation
(ref by_feature/automatic_gradient_accumulation.py).

Combines `find_executable_batch_size` with gradient accumulation: when the
per-step batch must shrink to fit memory, the accumulation step count grows
so the EFFECTIVE batch (observed_batch_size) stays constant.
"""

from __future__ import annotations

import argparse

import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import find_executable_batch_size, set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    observed_batch_size = args.batch_size  # the effective batch we promise
    ds = RegressionDataset(length=256, seed=args.seed)

    @find_executable_batch_size(starting_batch_size=observed_batch_size)
    def inner_training_loop(batch_size):
        # keep the effective batch: accumulate over the shrink factor
        accum = observed_batch_size // batch_size
        accelerator.gradient_accumulation_steps = accum
        accelerator.print(f"batch_size={batch_size} accumulation={accum}")
        accelerator.free_memory()
        loader = accelerator.prepare(
            [{"x": ds.x[i : i + batch_size], "y": ds.y[i : i + batch_size]}
             for i in range(0, 256, batch_size)]
        )
        ts = accelerator.prepare(TrainState.create(
            apply_fn=None, params=regression_params(), tx=optax.adam(args.lr),
            use_grad_accum_buffer=accum > 1,
        ))
        step = accelerator.train_step(regression_loss)
        for _ in range(args.num_epochs):
            for batch in loader:
                ts, m = step(ts, batch)
        return {"loss": float(m["loss"]), "batch_size": batch_size,
                "accumulation": accum}

    metrics = inner_training_loop()
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64,
                        help="effective batch size to maintain")
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
