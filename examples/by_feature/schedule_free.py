"""Feature: schedule-free optimization (ref by_feature/schedule_free.py).

The reference wraps torch's `schedulefree.AdamWScheduleFree`; the JAX-native
equivalent is `optax.contrib.schedule_free` over any base optimizer — no LR
schedule, no `scheduler.step()` bookkeeping. Eval uses the schedule-free
EVAL parameters (`schedule_free_eval_params`), mirroring the reference's
`optimizer.eval()` mode switch.
"""

from __future__ import annotations

import argparse

import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_forward,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    ds = RegressionDataset(length=256, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 256, bs)]
    )
    tx = optax.contrib.schedule_free(
        optax.adam(args.lr, b1=0.0), learning_rate=args.lr, b1=0.9
    )
    ts = accelerator.prepare(
        TrainState.create(apply_fn=None, params=regression_params(), tx=tx)
    )
    step = accelerator.train_step(regression_loss)
    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)

    # the reference flips optimizer.eval(); here the eval params are derived
    eval_params = optax.contrib.schedule_free_eval_params(ts.opt_state, ts.params)
    preds = regression_forward(eval_params, ds.x)
    metrics = {
        "train_loss": float(m["loss"]),
        "eval_mse": float(np.mean((np.asarray(preds) - ds.y) ** 2)),
    }
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=4)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
