"""Feature: FSDP (full param sharding) + peak-memory tracking
(ref by_feature/fsdp_with_peak_mem_tracking.py).

`FullyShardedDataParallelPlugin` lowers to parameter sharding on the mesh
`fsdp` axis (ZeRO-3 under GSPMD); `device_memory_stats`/`live_array_bytes`
replace the reference's TorchTracemalloc context.
"""

from __future__ import annotations

import argparse

import jax
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import bert
from accelerate_tpu.profiler import device_memory_stats, live_array_bytes
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

import numpy as np


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=args.sharding_strategy,
            activation_checkpointing=args.activation_checkpointing,
        ),
        gradient_clipping=1.0,
    )
    set_seed(args.seed)
    cfg = bert.BertConfig.tiny(remat=args.activation_checkpointing) \
        if args.tiny else bert.BertConfig.base(remat=args.activation_checkpointing)

    rng = np.random.default_rng(args.seed)
    n, seq, bs = 128, 64, args.batch_size
    ids = rng.integers(4, cfg.vocab_size, (n, seq)).astype(np.int32)
    labels = rng.integers(0, 2, (n,)).astype(np.int32)
    loader = accelerator.prepare(
        [{"input_ids": ids[i : i + bs], "labels": labels[i : i + bs]}
         for i in range(0, n, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=bert.init_params(cfg, jax.random.key(args.seed)),
        tx=optax.adamw(args.lr),
    ))
    step = accelerator.train_step(lambda p, b: bert.classification_loss(cfg, p, b))

    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)
        stats = device_memory_stats()
        metrics = {
            "epoch": epoch,
            "loss": float(m["loss"]),
            "live_array_mb": live_array_bytes() / 2**20,
            "peak_mb": stats.get("peak_bytes_in_use", 0) / 2**20,
        }
        accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--sharding_strategy", default="FULL_SHARD")
    parser.add_argument("--activation_checkpointing", action="store_true")
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
