"""Feature: Local SGD (ref by_feature/local_sgd.py).

Each host trains without cross-host gradient sync; every `local_sgd_steps`
the parameter pytrees are averaged across host processes (the slow-link DCN
sync the technique exists to amortize). Within a slice, GSPMD still averages
over ICI implicitly — that part is free on TPU.
"""

from __future__ import annotations

import argparse

import optax

from accelerate_tpu import LocalSGD, TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(
        gradient_accumulation_steps=args.gradient_accumulation_steps
    )
    set_seed(args.seed)
    ds = RegressionDataset(length=256, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 256, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr),
        use_grad_accum_buffer=args.gradient_accumulation_steps > 1,
    ))
    step = accelerator.train_step(regression_loss)

    for epoch in range(args.num_epochs):
        with LocalSGD(accelerator, local_sgd_steps=args.local_sgd_steps) as local_sgd:
            for batch in loader:
                with accelerator.accumulate():
                    ts, m = step(ts, batch)
                # threads the averaged state back (functional contract)
                ts = local_sgd.step(ts)

    metrics = {"loss": float(m["loss"])}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local_sgd_steps", type=int, default=8)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
