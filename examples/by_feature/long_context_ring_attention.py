"""Feature: long-context training with ring-attention context parallelism.

No reference equivalent (the reference has no context parallelism —
SURVEY.md §2.2 marks CP absent); this is the long-context answer built on
`parallel/ring_attention.py`: the sequence dim shards over the mesh `seq`
axis, K/V chunks rotate with `lax.ppermute` (exactly one collective-permute
per rotated buffer — pinned in tests/test_compiled_contracts.py), and per
chunk the attention is flash-rate.

Two equivalent ways to turn it on:

1. In code (this script): `ContextParallelPlugin(mode="ring", seq_degree=N)`
   plus `LlamaConfig(attention_backend="ring")`.
2. From the launcher, with no code change:
     accelerate-tpu launch --context_parallel_mode ring \\
         --context_parallel_degree 2 train.py
   (the env protocol resolves the plugin inside `Accelerator.__init__`).

Run: python examples/by_feature/long_context_ring_attention.py --tiny
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.utils import ContextParallelPlugin, set_seed


def training_function(args) -> dict:
    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_clipping=1.0,
        context_parallel_plugin=ContextParallelPlugin(
            mode=args.cp_mode, seq_degree=args.cp_degree
        ),
    )
    # the seq axis must divide the sequence; everything else is the
    # ordinary causal-LM loop — the ring rides inside the attention op
    if args.tiny:
        cfg = llama.LlamaConfig.tiny(
            attention_backend=args.cp_mode,
            max_position_embeddings=max(256, args.seq_len),
        )
    else:
        cfg = llama.LlamaConfig(
            attention_backend=args.cp_mode,
            max_position_embeddings=args.seq_len,
        )
    params = llama.init_params(cfg, jax.random.key(args.seed))
    state = accelerator.prepare(
        TrainState.create(apply_fn=None, params=params, tx=optax.adamw(args.lr))
    )
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(
        0, cfg.vocab_size, (args.batch_size, args.seq_len + 1)
    ).astype(np.int32)
    loader = accelerator.prepare([{"input_ids": ids}])
    step = accelerator.train_step(
        lambda p, b: llama.causal_lm_loss(cfg, p, b)
    )
    losses = []
    for _ in range(args.steps):
        for batch in loader:
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    accelerator.print(
        f"cp_mode={args.cp_mode} seq={args.seq_len} "
        f"mesh={dict(accelerator.mesh.shape)} "
        f"loss {losses[0]:.4f} -> {losses[-1]:.4f}"
    )
    return {"loss": losses[-1], "first_loss": losses[0]}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--cp_mode", choices=["ring", "ulysses"],
                        default="ring")
    parser.add_argument("--cp_degree", type=int, default=2,
                        help="size of the seq mesh axis")
    parser.add_argument("--seq_len", type=int, default=512)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--mixed_precision", default="no",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--tiny", action="store_true",
                        help="tiny model (CI/CPU smoke)")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
