"""Feature: causal-LM pretraining on an explicit tp/fsdp/data mesh
(ref by_feature/megatron_lm_gpt_pretraining.py — Megatron TP+PP+DP GPT
pretraining; here one GSPMD mesh replaces the Megatron engine).

`MeshConfig(axes={"data": d, "fsdp": f, "model": t})` is the whole
parallelism config: the sharding planner emits Megatron-style row/column
PartitionSpecs for the `model` axis, ZeRO-3 parameter sharding on `fsdp`,
and batch sharding on `data` — XLA inserts the all-gathers/reduce-scatters
the Megatron runtime hand-schedules.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.utils import MeshConfig, set_seed


def synthetic_corpus(vocab: int, n_docs: int, seq: int, seed: int):
    """Markov-ish token stream so the LM loss has learnable structure."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, vocab, (n_docs, seq + 1)).astype(np.int32)
    base[:, 1::2] = (base[:, 0:-1:2] + 1) % vocab  # every odd token predictable
    return base


def training_function(args) -> dict:
    axes = {}
    if args.dp > 0:
        axes["data"] = args.dp
    if args.fsdp > 0:
        axes["fsdp"] = args.fsdp
    if args.tp > 1:
        axes["model"] = args.tp
    if not axes:
        axes = {"data": -1}
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        mesh_config=MeshConfig(axes=axes),
        gradient_clipping=1.0,
    )
    accelerator.print(f"mesh: {dict(accelerator.mesh.shape)}")
    set_seed(args.seed)

    cfg = llama.LlamaConfig.tiny(remat=args.activation_checkpointing) \
        if args.tiny else llama.LlamaConfig(
            hidden_size=1024, intermediate_size=2816, num_hidden_layers=8,
            num_attention_heads=8, num_key_value_heads=8,
            max_position_embeddings=args.seq_len,
            remat=args.activation_checkpointing,
        )
    seq = min(args.seq_len, cfg.max_position_embeddings)
    corpus = synthetic_corpus(cfg.vocab_size, 16 * args.batch_size, seq, args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"input_ids": corpus[i : i + bs]} for i in range(0, len(corpus), bs)]
    )
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, 10, args.num_epochs * len(loader)
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=llama.init_params(cfg, jax.random.key(args.seed)),
        tx=optax.adamw(schedule, weight_decay=0.01),
    ))
    step = accelerator.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))

    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)
        accelerator.print({"epoch": epoch, "lm_loss": float(m["loss"])})
    return {"lm_loss": float(m["loss"])}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--tp", type=int, default=1, help="model (tensor) axis size")
    parser.add_argument("--fsdp", type=int, default=0, help="fsdp axis size (0=off)")
    parser.add_argument("--dp", type=int, default=-1, help="data axis (-1=rest)")
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--activation_checkpointing", action="store_true")
    parser.add_argument("--seq_len", type=int, default=512)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
