"""Feature: experiment tracking (ref by_feature/tracking.py).

`log_with` accepts any of {jsonl, tensorboard, wandb, mlflow, comet_ml, aim,
clearml, dvclive} or "all" for every backend importable in the environment;
`init_trackers` stores the run config, `log` fans metrics out, and
`end_training` closes every backend.
"""

from __future__ import annotations

import argparse
import tempfile

import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import ProjectConfiguration, set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(
        log_with=args.log_with,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir, logging_dir=args.project_dir
        ),
    )
    set_seed(args.seed)
    accelerator.init_trackers("tracking_example", config=vars(args))

    ds = RegressionDataset(length=128, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 128, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
    ))
    step = accelerator.train_step(regression_loss)

    overall_step = 0
    for epoch in range(args.num_epochs):
        total = 0.0
        for batch in loader:
            ts, m = step(ts, batch)
            total += float(m["loss"])
            overall_step += 1
        accelerator.log(
            {"train_loss": total / len(loader), "epoch": epoch}, step=overall_step
        )
    accelerator.end_training()
    metrics = {"train_loss": total / len(loader)}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--log_with", default="jsonl")
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    if args.project_dir is None:
        with tempfile.TemporaryDirectory() as tmp:
            args.project_dir = tmp
            training_function(args)
    else:
        training_function(args)


if __name__ == "__main__":
    main()
