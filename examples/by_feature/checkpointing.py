"""Feature: versioned checkpointing + resume (ref by_feature/checkpointing.py).

`save_state` writes `checkpoints/checkpoint_{n}` (model/optimizer/scheduler/
sampler/RNG) under `ProjectConfiguration(automatic_checkpoint_naming=True)`;
`load_state` restores the latest; `skip_first_batches` resumes mid-epoch.
"""

from __future__ import annotations

import argparse
import tempfile

import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import ProjectConfiguration, set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(project_config=ProjectConfiguration(
        project_dir=args.project_dir, automatic_checkpoint_naming=True,
        total_limit=3,
    ))
    set_seed(args.seed)
    ds = RegressionDataset(length=128, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 128, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
    ))
    step = accelerator.train_step(regression_loss)

    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)
        accelerator.save_state(state=ts)  # one versioned dir per epoch

    # resume from the latest checkpoint and continue one epoch
    restored = accelerator.load_state(state=ts)
    ts = restored.get("train_states", [ts])[0]
    done = int(ts.step)
    resume_batch = done % len(loader)
    for batch in accelerator.skip_first_batches(loader, resume_batch):
        ts, m = step(ts, batch)

    metrics = {"loss": float(m["loss"]), "resumed_at_step": done}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--project_dir", default=None)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    if args.project_dir is None:
        with tempfile.TemporaryDirectory() as tmp:
            args.project_dir = tmp
            training_function(args)
    else:
        training_function(args)


if __name__ == "__main__":
    main()
