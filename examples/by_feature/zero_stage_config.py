"""Feature: ZeRO-stage configuration via DeepSpeedPlugin
(ref by_feature/deepspeed_with_config_support.py — ds_config.json driving
deepspeed.initialize; here the plugin lowers to GSPMD axis assignments).

stage 0 → pure data parallel; stage 1/2 → optimizer-state (+grad) sharding;
stage 3 → full parameter sharding on the `fsdp` axis. The same training loop
runs under every stage — only the sharding plan changes.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import bert
from accelerate_tpu.utils import DeepSpeedPlugin, set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        deepspeed_plugin=DeepSpeedPlugin(
            zero_stage=args.zero_stage,
            gradient_clipping=1.0,
            offload_param_device=args.offload_param_device,
        ),
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    accelerator.print(
        f"zero_stage={args.zero_stage} mesh={dict(accelerator.mesh.shape)}"
    )
    set_seed(args.seed)
    cfg = bert.BertConfig.tiny() if args.tiny else bert.BertConfig.base()
    rng = np.random.default_rng(args.seed)
    n, seq, bs = 128, 64, args.batch_size
    ids = rng.integers(4, cfg.vocab_size, (n, seq)).astype(np.int32)
    labels = rng.integers(0, 2, (n,)).astype(np.int32)
    loader = accelerator.prepare(
        [{"input_ids": ids[i : i + bs], "labels": labels[i : i + bs]}
         for i in range(0, n, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=bert.init_params(cfg, jax.random.key(args.seed)),
        tx=optax.adamw(args.lr),
        use_grad_accum_buffer=args.gradient_accumulation_steps > 1,
    ))
    step = accelerator.train_step(lambda p, b: bert.classification_loss(cfg, p, b))

    for epoch in range(args.num_epochs):
        for batch in loader:
            ts, m = step(ts, batch)
        accelerator.print({"epoch": epoch, "loss": float(m["loss"])})
    return {"loss": float(m["loss"])}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--zero_stage", type=int, default=2, choices=[0, 1, 2, 3])
    parser.add_argument("--offload_param_device", default=None)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--mixed_precision", default="bf16",
                        choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--tiny", action="store_true")
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
