"""Feature: gradient accumulation (ref by_feature/gradient_accumulation.py).

`Accelerator(gradient_accumulation_steps=k)` + a TrainState with the
accumulation buffer: the optimizer applies every k micro-batches inside ONE
compiled step (`lax.cond` gates the apply — no Python-side scheduling), so
the loop body is identical to the no-accumulation case.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator(
        gradient_accumulation_steps=args.gradient_accumulation_steps
    )
    set_seed(args.seed)
    ds = RegressionDataset(length=256, seed=args.seed)
    bs = args.batch_size
    loader = accelerator.prepare(
        [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]} for i in range(0, 256, bs)]
    )
    ts = accelerator.prepare(TrainState.create(
        apply_fn=None, params=regression_params(), tx=optax.adam(args.lr),
        use_grad_accum_buffer=args.gradient_accumulation_steps > 1,
    ))
    step = accelerator.train_step(regression_loss)
    for epoch in range(args.num_epochs):
        for batch in loader:
            # accumulate() only tracks the sync flag for user-visible logic;
            # the compiled step already applies on the k-th micro-batch
            with accelerator.accumulate():
                ts, m = step(ts, batch)
    a, b = jax.device_get((ts.params["a"], ts.params["b"]))
    metrics = {"loss": float(m["loss"]), "a": float(a), "b": float(b)}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gradient_accumulation_steps", type=int, default=4)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=4)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
