"""Feature: OOM-retry with `find_executable_batch_size`
(ref by_feature/memory.py).

The decorated inner function receives the current batch size; on an XLA
RESOURCE_EXHAUSTED (or other OOM-classified) error it is re-invoked with the
batch size halved, after clearing compiled-program and buffer caches.
"""

from __future__ import annotations

import argparse

import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import find_executable_batch_size, set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    ds = RegressionDataset(length=256, seed=args.seed)

    @find_executable_batch_size(starting_batch_size=args.batch_size)
    def inner_training_loop(batch_size):
        accelerator.print(f"trying batch_size={batch_size}")
        accelerator.free_memory()
        loader = accelerator.prepare(
            [{"x": ds.x[i : i + batch_size], "y": ds.y[i : i + batch_size]}
             for i in range(0, 256, batch_size)]
        )
        ts = accelerator.prepare(TrainState.create(
            apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
        ))
        step = accelerator.train_step(regression_loss)
        for _ in range(args.num_epochs):
            for batch in loader:
                ts, m = step(ts, batch)
        return {"loss": float(m["loss"]), "batch_size": batch_size}

    metrics = inner_training_loop()
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
