"""Feature: k-fold cross validation (ref by_feature/cross_validation.py).

Each fold trains on k-1 splits and evaluates on the held-out one; fold
predictions are gathered with `gather_for_metrics` and the final ensembled
metric is computed over the out-of-fold predictions.
"""

from __future__ import annotations

import argparse

import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.test_utils.training import (
    RegressionDataset,
    regression_forward,
    regression_loss,
    regression_params,
)
from accelerate_tpu.utils import set_seed


def training_function(args) -> dict:
    accelerator = Accelerator()
    set_seed(args.seed)
    ds = RegressionDataset(length=240, seed=args.seed)
    k, bs = args.num_folds, args.batch_size
    fold_size = len(ds) // k
    fold_mse = []

    for fold in range(k):
        lo, hi = fold * fold_size, (fold + 1) * fold_size
        train_idx = np.concatenate([np.arange(0, lo), np.arange(hi, len(ds))])
        x_tr, y_tr = ds.x[train_idx], ds.y[train_idx]
        loader = accelerator.prepare(
            [{"x": x_tr[i : i + bs], "y": y_tr[i : i + bs]}
             for i in range(0, len(x_tr), bs)]
        )
        eval_loader = accelerator.prepare(
            [{"x": ds.x[i : i + bs], "y": ds.y[i : i + bs]}
             for i in range(lo, hi, bs)]
        )
        ts = accelerator.prepare(TrainState.create(
            apply_fn=None, params=regression_params(), tx=optax.adam(args.lr)
        ))
        step = accelerator.train_step(regression_loss)
        eval_step = accelerator.eval_step(lambda p, b: regression_forward(p, b["x"]))
        for _ in range(args.num_epochs):
            for batch in loader:
                ts, _ = step(ts, batch)
        preds, targets = [], []
        for batch in eval_loader:
            out = eval_step(ts.params, batch)
            out, y = accelerator.gather_for_metrics((out, batch["y"]))
            preds.append(np.asarray(out).reshape(-1))
            targets.append(np.asarray(y).reshape(-1))
        mse = float(np.mean((np.concatenate(preds) - np.concatenate(targets)) ** 2))
        fold_mse.append(mse)
        accelerator.print(f"fold {fold}: eval_mse={mse:.4f}")
        accelerator.free_memory()

    metrics = {"mean_mse": float(np.mean(fold_mse)), "folds": fold_mse}
    accelerator.print(metrics)
    return metrics


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_folds", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=42)
    training_function(parser.parse_args())


if __name__ == "__main__":
    main()
